"""Object-store FileIO: S3-semantics storage as a first-class design axis.

The reference treats rename-less stores as their own world: FileIO SPI plugins
under /root/reference/paimon-filesystems/ (paimon-s3, paimon-oss),
`FileIO.isObjectStore()` (fs/FileIO.java:66), and commits that run under an
external lock with an exists-check because "fs.rename may not return false if
target file already exists, or even not atomic"
(operation/FileStoreCommitImpl.java:948-957).

This module emulates those semantics faithfully over a local directory so the
whole store stack — commit CAS, catalog lock, crash oracle — runs against
them without network access:

- **PUT is atomic and last-writer-wins**: an object appears fully formed or
  not at all; concurrent overwrites race, last one wins (S3 PutObject).
- **Conditional PUT** (`If-None-Match: *`, supported by modern S3/GCS/Azure):
  exclusive create — exactly one of N racers succeeds.  `conditional_put=
  False` models legacy stores without it: exclusive create degrades to
  check-then-put, and `write_bytes(overwrite=False)` is NOT a CAS — such
  stores must commit under an external (e.g. jdbc) catalog lock.
- **No atomic rename**: rename is CopyObject + DeleteObject.  It is not
  exclusive (two racers can both "win", last copy wins) and the destination
  check is advisory TOCTOU.  `try_atomic_write` therefore NEVER uses rename
  here: with conditional put it is a direct conditional PUT; legacy mode is
  check-then-put (safe only under the catalog lock, which
  `atomic_write_supported=False` auto-engages in FileStoreCommit).
- **Flat namespace**: directories are prefixes.  mkdirs is a no-op, a
  "directory" exists iff some key carries the prefix, delete(recursive)
  deletes by prefix.
- **No hard links** exposed (LocalFileIO's link-based CAS trick is exactly
  what an object store cannot do).

Wire format on disk: keys become files under the root path; the staging dir
`.os-staging/` holds in-flight PUTs so visibility is always whole-object
(os.replace / os.link from a fully-written staged file).
"""

from __future__ import annotations

import os
import shutil
import uuid

from . import FileIO, FileStatus, register_file_io, split_scheme

__all__ = ["ObjectStoreFileIO"]


class ObjectStoreFileIO(FileIO):
    """See module docstring.  Paths: ``s3://<abs-local-path>`` (the local
    path backs the "bucket"); ``s3-legacy://`` is the same store without
    conditional PUT."""

    # rename is copy+delete: commits must run under the catalog lock
    atomic_write_supported = False

    def __init__(self, conditional_put: bool = True):
        self.conditional_put = conditional_put
        self.exclusive_create_supported = conditional_put

    # ---- key mapping ---------------------------------------------------
    def _p(self, path: str) -> str:
        return split_scheme(path)[1]

    def _staging(self, p: str) -> str:
        # stage inside the bucket root so os.replace/os.link stay one-fs;
        # walk up to an existing ancestor to anchor the staging dir
        anc = os.path.dirname(p)
        while anc and anc != "/" and not os.path.isdir(anc):
            anc = os.path.dirname(anc)
        d = os.path.join(anc or "/", ".os-staging")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, uuid.uuid4().hex)

    def _put(self, p: str, data: bytes) -> None:
        """Atomic-visibility overwrite PUT (last-writer-wins)."""
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = self._staging(p)
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)  # clobbers: last writer wins, content atomic

    def _put_if_absent(self, p: str, data: bytes) -> bool:
        """Conditional PUT (If-None-Match: *): True iff we created it."""
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = self._staging(p)
        with open(tmp, "wb") as f:
            f.write(data)
        try:
            os.link(tmp, p)  # emulates the store's server-side condition
            return True
        except FileExistsError:
            return False
        finally:
            os.unlink(tmp)

    # ---- FileIO surface ------------------------------------------------
    def read_bytes(self, path: str) -> bytes:
        with open(self._p(path), "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes, overwrite: bool = False) -> None:
        p = self._p(path)
        if overwrite:
            self._put(p, data)
            return
        if self.conditional_put:
            if not self._put_if_absent(p, data):
                raise FileExistsError(p)
            return
        # legacy store: no exclusive create. Advisory check + PUT — callers
        # writing uniquely-named objects (data files, manifests) are safe;
        # anything needing mutual exclusion must hold the catalog lock.
        if os.path.exists(p):
            raise FileExistsError(p)
        self._put(p, data)

    def exists(self, path: str) -> bool:
        # an object, or a "directory" (= some key has this prefix)
        return os.path.exists(self._p(path))

    def delete(self, path: str, recursive: bool = False) -> bool:
        p = self._p(path)
        try:
            if os.path.isdir(p):
                if recursive:
                    shutil.rmtree(p)  # prefix delete (batch DeleteObjects)
                else:
                    # directories are virtual: deleting a bare prefix with
                    # children is a no-op; an empty prefix "exists" only as
                    # a local-dir artifact, drop it
                    try:
                        os.rmdir(p)
                    except OSError:
                        return False
            else:
                os.remove(p)
            return True
        except FileNotFoundError:
            return False

    def mkdirs(self, path: str) -> None:
        # prefixes need no creation; materialize the local dir only so the
        # emulation's listings behave (harmless, objects still define truth)
        os.makedirs(self._p(path), exist_ok=True)

    def rename(self, src: str, dst: str) -> bool:
        """CopyObject + DeleteObject.  NOT atomic, NOT exclusive: the
        destination check is advisory (TOCTOU) — two racers can both return
        True with last-copy-wins.  Commit protocols must not use this as a
        CAS; `try_atomic_write` here never does."""
        s, d = self._p(src), self._p(dst)
        if not os.path.exists(s):
            return False
        if os.path.isdir(s):
            # virtual-dir rename = per-object copy (reference object stores
            # do exactly this server-side, O(objects))
            if os.path.exists(d):
                return False
            shutil.copytree(s, d)
            shutil.rmtree(s)
            return True
        if os.path.exists(d):  # advisory only
            return False
        with open(s, "rb") as f:
            self._put(d, f.read())
        os.remove(s)
        return True

    def list_status(self, path: str) -> list[FileStatus]:
        p = self._p(path)
        if not os.path.isdir(p):
            return []
        out = []
        for name in sorted(os.listdir(p)):
            if name == ".os-staging":
                continue
            fp = os.path.join(p, name)
            try:
                st = os.stat(fp)
            except FileNotFoundError:
                continue
            out.append(FileStatus(fp, st.st_size, os.path.isdir(fp), int(st.st_mtime * 1000)))
        return out

    def get_status(self, path: str) -> FileStatus:
        p = self._p(path)
        st = os.stat(p)
        return FileStatus(p, st.st_size, os.path.isdir(p), int(st.st_mtime * 1000))

    def open_input(self, path: str):
        return open(self._p(path), "rb")

    # ---- commit primitives (no rename!) --------------------------------
    def try_atomic_write(self, path: str, data: bytes) -> bool:
        """Reference FileIO#tryToWriteAtomic, object-store edition: PUT is
        already whole-object-atomic, so no temp+rename dance.  Conditional
        PUT makes this a true CAS; legacy mode is check-then-put and is only
        safe under the catalog lock (engaged automatically because
        atomic_write_supported is False)."""
        p = self._p(path)
        if self.conditional_put:
            return self._put_if_absent(p, data)
        if os.path.exists(p):
            return False
        self._put(p, data)
        return True

    def try_overwrite(self, path: str, data: bytes) -> bool:
        """Hints etc.: a plain overwrite PUT is atomic-visibility on an
        object store (reference S3 FileIO overwrites hint objects directly
        instead of delete+rename)."""
        self._put(self._p(path), data)
        return True


register_file_io("s3", lambda: ObjectStoreFileIO(conditional_put=True))
register_file_io("oss", lambda: ObjectStoreFileIO(conditional_put=True))
register_file_io("s3-legacy", lambda: ObjectStoreFileIO(conditional_put=False))
