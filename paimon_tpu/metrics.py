"""Engine-neutral metrics kernel.

Parity: /root/reference/paimon-core/.../metrics/ — MetricRegistry, groups,
Counter/Gauge/Histogram; instrumented scan/commit/compaction
(operation/metrics/ScanMetrics, CommitMetrics, CompactionMetrics). External
engines bridge this registry to their own metric systems, exactly like the
reference bridges to Flink/Spark.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Callable

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricGroup",
    "MetricRegistry",
    "registry",
    "timed",
    "compaction_metrics",
    "decode_metrics",
    "dict_metrics",
    "encode_metrics",
    "gateway_metrics",
    "get_metrics",
    "io_metrics",
    "join_metrics",
    "lanes_metrics",
    "mesh_metrics",
    "pallas_metrics",
    "pipeline_metrics",
    "soak_metrics",
    "sql_metrics",
    "sub_metrics",
]


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def count(self) -> int:
        return self._v


class Gauge:
    def __init__(self, fn: Callable[[], float] | None = None):
        self._fn = fn
        self._v: float = 0.0

    def set(self, v: float) -> None:
        self._v = v

    @property
    def value(self) -> float:
        return self._fn() if self._fn is not None else self._v


class Histogram:
    """Sliding-window histogram (reference uses a 100-sample window)."""

    def __init__(self, window: int = 100):
        self.window = window
        self._values: list[float] = []
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        with self._lock:
            self._values.append(v)
            if len(self._values) > self.window:
                self._values.pop(0)

    @property
    def count(self) -> int:
        return len(self._values)

    @property
    def mean(self) -> float:
        return sum(self._values) / len(self._values) if self._values else 0.0

    @property
    def max(self) -> float:
        return max(self._values) if self._values else 0.0

    @property
    def min(self) -> float:
        return min(self._values) if self._values else 0.0

    @property
    def last(self) -> float:
        """Most recent sample — per-operation readout for benches/tests."""
        return self._values[-1] if self._values else 0.0


class MetricGroup:
    def __init__(self, name: str, tags: dict[str, str] | None = None):
        self.name = name
        self.tags = tags or {}
        self.metrics: dict[str, object] = {}

    def counter(self, name: str) -> Counter:
        return self.metrics.setdefault(name, Counter())  # type: ignore[return-value]

    def gauge(self, name: str, fn: Callable[[], float] | None = None) -> Gauge:
        return self.metrics.setdefault(name, Gauge(fn))  # type: ignore[return-value]

    def histogram(self, name: str, window: int = 100) -> Histogram:
        return self.metrics.setdefault(name, Histogram(window))  # type: ignore[return-value]


class MetricRegistry:
    def __init__(self):
        self.groups: dict[tuple, MetricGroup] = {}
        self._lock = threading.Lock()

    def group(self, name: str, **tags: str) -> MetricGroup:
        key = (name, tuple(sorted(tags.items())))
        with self._lock:
            if key not in self.groups:
                self.groups[key] = MetricGroup(name, tags)
            return self.groups[key]

    def snapshot(self) -> dict:
        out: dict = {}
        for (name, tags), group in self.groups.items():
            entry = {}
            for mname, m in group.metrics.items():
                if isinstance(m, Counter):
                    entry[mname] = m.count
                elif isinstance(m, Gauge):
                    entry[mname] = m.value
                elif isinstance(m, Histogram):
                    entry[mname] = {"count": m.count, "mean": m.mean, "max": m.max}
            out[name if not tags else f"{name}{dict(tags)}"] = entry
        return out

    def reset(self) -> None:
        with self._lock:
            self.groups.clear()


registry = MetricRegistry()


def decode_metrics() -> MetricGroup:
    """The decode{...} group (native parquet page-decode subsystem,
    paimon_tpu.decode). Canonical members — counters: pages_decoded,
    pages_skipped (dead under compressed-domain pushdown, never expanded),
    bytes_expanded (materialized value bytes), rows_pruned, files_native,
    files_fallback (fell back to the arrow decoder); histograms: file_ms
    (whole-file native decode wall millis), pushdown_ms (per row group).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("decode")


def dict_metrics() -> MetricGroup:
    """The dict{...} group (compressed-domain merge, paimon_tpu.ops.dicts +
    the code-domain reader mode in paimon_tpu.decode). Canonical members —
    counters: pools_unified (per-input sorted pools merged into a shared
    merge domain), codes_remapped (rows whose dictionary codes re-mapped
    through a unification/sort gather), rows_code_domain (rows delivered by
    a reader as dictionary codes instead of expanded strings),
    fallback_expanded (rows that fell back to the expanded-string path: a
    non-dictionary chunk, a pool past merge.dict-domain.pool-limit, or a
    consumer that needed real values); histogram: unify_ms (host wall
    millis unifying pools — object work at |pool| scale, never |rows|).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("dict")


def encode_metrics() -> MetricGroup:
    """The encode{...} group (native parquet page-encode subsystem,
    paimon_tpu.encode — the write-side mirror of decode{...}). Canonical
    members — counters: pages_written (data pages), bytes_written (file
    bytes produced natively), dict_pages (dictionary pages emitted),
    files_native, files_fallback (fell back to the arrow writer on an
    unsupported shape); histograms: encode_ms (whole-file native encode
    wall millis), stats_ms (chunk min/max statistics portion). Resolved per
    call so registry.reset() in tests swaps the group out."""
    return registry.group("encode")


def pipeline_metrics() -> MetricGroup:
    """The pipeline{...} group (pipelined split scheduler,
    paimon_tpu.parallel.pipeline). Canonical members — counter:
    splits_prefetched (items submitted ahead of the consumer); gauge:
    queue_depth_high_water (max items in flight — bounded by
    scan.prefetch-splits + 1, the memory high-water guard); histograms per
    stage: {stage}_busy_ms (worker wall time per item) and {stage}_wait_ms
    (consumer blocked waiting for the head-of-line item), stage in
    {scan, compact, flush}. Resolved per call so registry.reset() in tests
    swaps the group out."""
    return registry.group("pipeline")


def lanes_metrics() -> MetricGroup:
    """The lanes{...} group (key-lane compression layer, paimon_tpu.ops.lanes).
    Canonical members — counters: plans (merges planned), lanes_in (logical
    uint32 key lanes entering the planner), lanes_out (physical sort operands
    after truncation + packing, incl. the OVC lane when present), bytes_saved
    (host->device key-lane bytes elided vs the uncompressed upload),
    ovc_merges (merges that carried an offset-value code lane through the
    sort). Resolved per call so registry.reset() in tests swaps the group
    out."""
    return registry.group("lanes")


def join_metrics() -> MetricGroup:
    """The join{...} group (device-side skew-aware joins, paimon_tpu.ops.
    join, surfaced through SQL JOIN and lookup joins). Canonical members —
    counters: joins (two-batch join_batches calls), index_probes (cached
    JoinIndex probe calls: the vectorized lookup path), rows_probed,
    rows_matched, hash_joins (single fused key operand: binary-search
    probe), sort_merge_joins (multi-operand keys through the
    sorted_segments seam), code_domain_joins (joins where at least one key
    column matched on unified dictionary codes with zero string
    materialization), skew_keys (heavy-hitter keys whose probe rows were
    split across partitions), skew_split_rows (probe rows so split);
    histograms: build_ms (key encode + lane planning), probe_ms (kernel +
    pair expansion). Resolved per call so registry.reset() in tests swaps
    the group out."""
    return registry.group("join")


def mesh_metrics() -> MetricGroup:
    """The mesh{...} group (mesh-sharded execution layer,
    paimon_tpu.parallel.mesh_exec). Canonical members — counters:
    buckets_sharded (per-bucket merge jobs executed through the mesh),
    shards (shard_map / key-axis collective invocations), pad_rows (padding
    overhead: allocated minus valid rows across batched calls),
    exchange_rows (rows moved through key-axis range-shuffle collectives);
    histograms: device_busy_ms (wall millis per batched device call),
    feeder_wait_ms (consumer blocked on the host-side split feeder).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("mesh")


def soak_metrics() -> MetricGroup:
    """The soak{...} group (writer flow control, core.admission, and the
    traffic-soak harness, service.soak). Canonical members — counters:
    commits_ok (committer rounds fully landed), commits_retried (CAS retry
    rounds absorbed across commits), commits_conflict_replanned (conflict
    events survived by abandoning stolen buckets or adopting the landed
    APPEND phase), writes_throttled (admissions that blocked at the
    stop trigger or the pending-flush cap), writes_rejected (throttled
    writes that hit write.buffer.block-timeout and raised
    WriterBackpressureError), procs_spawned / procs_killed /
    procs_respawned (process-grain soak supervisor: writer/reader OS
    processes started, kill -9'd at crash points or at random, and brought
    back), crash_recoveries (respawned writers that resolved a landed-but-
    unacked commit from the snapshot chain instead of replaying it),
    shed_requests (ingest requests answered with a typed BUSY by a network
    server while the writer was throttling/rejecting); gauges: read_p50_ms,
    read_p99_ms (snapshot read latency percentiles, set by the soak
    harness); histogram: backpressure_ms (time writers spent blocked in
    admission). Resolved per call so registry.reset() in tests swaps the
    group out."""
    return registry.group("soak")


def pallas_metrics() -> MetricGroup:
    """The pallas{...} group (fused merge kernels, paimon_tpu.ops.
    pallas_kernels, routed by sort-engine=pallas). Canonical members —
    counters: kernels_launched (merge dispatches routed through the pallas
    engine), tiles (pallas grid steps: 1 per fused sort+segment call, one
    per _BLOCK rows for the post-lax.sort boundary sweep), fallback_xla
    (dispatches that exceeded the fused kernel's VMEM admission test — or
    found no pallas at all — and fell back to lax.sort; the boundary sweep
    still runs in pallas when available); histogram: kernel_ms (wall millis
    of synchronously-resolved fused dispatches: merge_plan and the fused
    partial-update/aggregate kernels; async dedup dispatch latency is
    benchmarked in benchmarks/pallas_bench.py instead). Resolved per call
    so registry.reset() in tests swaps the group out."""
    return registry.group("pallas")


def compaction_metrics() -> MetricGroup:
    """The compaction{...} group (LSM compaction execution, core.compact,
    plus the adaptive scheduler, table.compactor.AdaptiveCompactorService).
    Canonical members — counters: compactions, files_rewritten (execution
    side, incremented per committed rewrite), adaptive_runs (buckets the
    adaptive scheduler compacted), deferred_buckets (buckets with pending
    sorted runs the policy deliberately left for later — cold or below
    trigger), adaptive_conflicts (adaptive rounds abandoned to a rival
    commit), admission_waits (ingest commits that blocked in the service's
    debt-admission gate because a target bucket sat at/over the read-amp
    ceiling); gauges: debt_files / debt_bytes (files and bytes above one
    run per bucket, summed over buckets — the compaction debt the
    scheduler is draining), read_amplification_p99 (p99 of per-bucket
    sorted-run counts at the last observation — the bound
    compaction.adaptive.read-amp-ceiling enforces); histogram: duration_ms
    (per compaction execution). Resolved per call so registry.reset() in
    tests swaps the group out."""
    return registry.group("compaction")


def get_metrics() -> MetricGroup:
    """The get{...} group (batched point-lookup serving, paimon_tpu.table.
    get + lookup.index, surfaced as LocalTableQuery.get_batch, the KV
    server's get_batch method and Flight do_action("get_batch")). Canonical
    members — counters: gets (probe keys served, found or not), keys_probed
    (key x surviving-file probe work actually executed), files_pruned (data
    files skipped with NO data IO: key-range or bloom key-index verdict),
    index_hits (files whose PTIX key bloom was consulted), memtable_hits
    (keys whose winning row came from the read-your-writes delta tier:
    an attached writer's memtable or its not-yet-committed level-0 files),
    busy_rejected (get_batch requests a server answered with a typed BUSY
    because lookup.get.max-inflight was saturated); histogram: probe_ms
    (end-to-end get_batch wall millis per call); gauge: p99_us (per-key p99
    latency in microseconds, set by the serving soak / benchmark).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("get")


def io_metrics() -> MetricGroup:
    """The io{...} group (resilience subsystem). Canonical members —
    counters: retries (transient faults absorbed by RetryingFileIO),
    giveups (ops that exhausted fs.retry.max-attempts), timeouts (ops that
    blew the fs.io.timeout deadline), cleanup_failures (non-fatal failures
    while deleting tmp/abandoned files in commit cleanup / expire / orphan
    sweep), orphans_removed; histogram: backoff_ms (individual retry
    sleeps). Resolved per call so registry.reset() in tests swaps the group
    out."""
    return registry.group("io")


def cluster_metrics() -> MetricGroup:
    """The cluster{...} group (coordinator/worker mesh execution,
    paimon_tpu.service.cluster). Canonical members — counters:
    workers_registered (worker registrations, respawned incarnations
    included), rounds_committed (ingest rounds the coordinator committed on
    behalf of workers), commits_rejected_stale (shipped CommitMessages
    refused because a bucket's assignment epoch advanced past the shipper's
    — the reassignment fence that prevents double-apply), reassignments
    (bucket ownership moves after a missed-heartbeat death), compact_tasks
    (compaction decisions dispatched to owning workers),
    compact_commits (worker-executed compaction results the coordinator
    committed), compact_conflicts (shipped compaction results abandoned to
    a rival commit), admit_denied (worker admit RPCs answered not-admitted
    because a target bucket sat at/over the read-amp ceiling — the
    cluster-wide debt gate), charges_released (in-flight debt charges
    dropped when their owning worker died), serve_gets (get_batch requests
    served by worker serving planes), serve_subscribe_polls (subscribe
    long-polls served by workers), join_parts_served (distributed join
    partitions executed on workers), rescales (completed cross-worker
    bucket rescales: schema bump + OVERWRITE snapshot landed and routes
    republished), handoffs (planned worker admits/retires that moved bucket
    ranges without a death timeout), replica_reads (serve reads a client
    routed to a non-primary replica owner). Gauges: workers_live,
    buckets_assigned, replicas_active (bucket->replica grants currently
    live). Resolved per call so registry.reset() in tests swaps the group
    out."""
    return registry.group("cluster")


def sql_metrics() -> MetricGroup:
    """The sql{...} group (distributed SQL scatter-gather,
    paimon_tpu.sql.cluster + the shared GROUP BY segment-reduce in
    sql.select / ops.aggregates). Canonical members — counters: fragments
    (per-worker scan fragments dispatched), fragments_retried (fragments
    re-dispatched after a worker death or connection loss),
    partials_combined (worker partial-aggregate payloads folded at the
    coordinator), rows_reduced_device (input rows reduced by the jitted
    segment-reduce kernel — single-process GROUP BY and worker partials
    both count; the numpy twin does not), code_domain_groups (groups whose
    keys travelled coordinator-ward as dictionary codes + pruned pools,
    never expanded), rows_streamed (non-aggregate rows gathered back
    Arrow-encoded), fragment_cache_hits (aggregate queries answered from
    the coordinator's fragment-result cache — same snapshot, same
    bucket-layout epoch, same fragment signature — without any worker RPC),
    shuffle_rounds (GROUP BY queries that combined via worker↔worker
    shuffle exchange instead of at the coordinator), parts_exchanged
    (nonempty group-domain hash partitions shipped worker→worker over
    exchange_part), exchange_bytes (approximate wire bytes of those
    parts), shuffle_retried (shuffle recovery actions: a range re-homed
    off a dead owner, or a missing part reshipped/re-executed);
    histograms: scatter_ms (dispatch + worker execution + gather wall
    millis per query), combine_ms (coordinator-side SERIAL combine stage
    millis per aggregate query: partial payload decode + second-stage
    unify/reduce — or, under shuffle, reduced-range decode + concat —
    + final batch assembly; RPC wait excluded, so classic vs shuffle
    readings compare the exact work the shuffle plane moves off the
    coordinator), shuffle_ms (scatter + exchange + per-range fold +
    concat wall millis per shuffled aggregate).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("sql")


def gateway_metrics() -> MetricGroup:
    """The gateway{...} group (multi-tenant front door,
    paimon_tpu.service.gateway). Canonical members — counters: requests
    (every request entering the gateway, any kind), admitted (requests that
    passed per-tenant QoS admission), sheds_typed (requests refused with a
    canonical ShedInfo — tenant budget, write backpressure, subscriber
    shed), sheds_untyped (client-observed failures under pressure that were
    NOT a typed shed; the storm harness counts these and asserts ZERO),
    hedges_issued (read RPCs re-issued to a secondary worker past
    gateway.hedge.deadline-ms), hedges_won (hedges where the secondary's
    answer was used), hedges_cancelled (loser attempts aborted after a
    winner returned), route_failovers (RPCs or bucket-owner lookups that
    fell over to a live secondary because the routed worker was dead or
    mid-respawn — the mega soak's kill schedule makes these routine; each
    one is a request SERVED, not shed); histograms: put_ms / get_batch_ms / subscribe_ms /
    sql_ms (per-kind gateway wall millis, all tenants mixed — the
    per-tenant decayed percentiles live in Gateway.slo()). Resolved per
    call so registry.reset() in tests swaps the group out."""
    return registry.group("gateway")


def sub_metrics() -> MetricGroup:
    """The sub{...} group (streaming CDC subscription service,
    paimon_tpu.service.subscription). Canonical members — gauges:
    subscribers (live subscribers across hubs), lag_snapshots (max over
    subscribers of frontier minus its next-expected snapshot — how far the
    slowest live reader trails the chain), queue_high_water (max batches
    observed in any subscriber queue, bounded by subscription.queue-depth);
    counters: batches_fanned (ChangelogBatch deliveries: one per subscriber
    per snapshot, live fan-out and catch-up replay both count),
    rows_fanned (rows delivered, rows x subscribers), decode_reuse_hits
    (deliveries that reused an already-decoded batch: live fan-out beyond
    the first subscriber plus catch-up reads served from the data-file
    cache — the decode-once proof, vs decode{pages_decoded} which stays
    flat in subscriber count), shed_subscribers (slow consumers shed with
    the typed SubscriberShedError carrying their durable restart offset).
    Resolved per call so registry.reset() in tests swaps the group out."""
    return registry.group("sub")


class timed:
    """Context manager recording wall millis into a histogram."""

    def __init__(self, histogram: Histogram):
        self.histogram = histogram

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.histogram.update((time.perf_counter() - self._t0) * 1000)
        return False
