"""SQL-style type system with field-id based schema evolution.

TPU-first design notes: every fixed-width type maps onto a numpy dtype that the
column-batch model (paimon_tpu.data.batch) stores directly, so predicate masks,
normalized sort keys, and merge kernels operate on dense vectors. Variable-width
types (STRING/BYTES) live host-side and enter device kernels only as
dictionary ranks (paimon_tpu.data.keys).

Capability parity with the reference type kernel:
  /root/reference/paimon-common/src/main/java/org/apache/paimon/types/ —
  DataType subclasses, RowType, DataField (field-id based evolution),
  RowKind (+I/-U/+U/-D) in types/RowKind.java.
"""

from __future__ import annotations

import enum
import json
import re
from dataclasses import dataclass, field, replace
from typing import Any, Iterable, Optional

import numpy as np

__all__ = [
    "TypeRoot",
    "DataType",
    "ArrayType",
    "MapType",
    "DataField",
    "RowType",
    "RowKind",
    "TINYINT",
    "SMALLINT",
    "INT",
    "BIGINT",
    "FLOAT",
    "DOUBLE",
    "BOOLEAN",
    "STRING",
    "CHAR",
    "VARCHAR",
    "BYTES",
    "DATE",
    "TIME",
    "TIMESTAMP",
    "DECIMAL",
    "parse_type",
]


class TypeRoot(str, enum.Enum):
    """Logical type families (reference: types/DataTypeRoot.java)."""

    BOOLEAN = "BOOLEAN"
    TINYINT = "TINYINT"
    SMALLINT = "SMALLINT"
    INT = "INT"
    BIGINT = "BIGINT"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    DECIMAL = "DECIMAL"
    CHAR = "CHAR"
    VARCHAR = "VARCHAR"  # STRING == VARCHAR(max)
    BINARY = "BINARY"
    VARBINARY = "VARBINARY"  # BYTES == VARBINARY(max)
    DATE = "DATE"
    TIME = "TIME"
    TIMESTAMP = "TIMESTAMP"
    TIMESTAMP_LTZ = "TIMESTAMP_LTZ"
    ARRAY = "ARRAY"
    MAP = "MAP"
    ROW = "ROW"


_FIXED_NUMPY = {
    TypeRoot.BOOLEAN: np.dtype(np.bool_),
    TypeRoot.TINYINT: np.dtype(np.int8),
    TypeRoot.SMALLINT: np.dtype(np.int16),
    TypeRoot.INT: np.dtype(np.int32),
    TypeRoot.BIGINT: np.dtype(np.int64),
    TypeRoot.FLOAT: np.dtype(np.float32),
    TypeRoot.DOUBLE: np.dtype(np.float64),
    TypeRoot.DATE: np.dtype(np.int32),  # days since epoch
    TypeRoot.TIME: np.dtype(np.int32),  # millis of day
    TypeRoot.TIMESTAMP: np.dtype(np.int64),  # micros since epoch
    TypeRoot.TIMESTAMP_LTZ: np.dtype(np.int64),
    TypeRoot.DECIMAL: np.dtype(np.int64),  # unscaled long (precision <= 18)
}

_MAX_LEN = 2147483647


@dataclass(frozen=True)
class DataType:
    """A logical type instance: root + nullability + parameters."""

    root: TypeRoot
    nullable: bool = True
    # length for CHAR/VARCHAR/BINARY/VARBINARY; precision for TIMESTAMP/DECIMAL
    length: int | None = None
    precision: int | None = None
    scale: int | None = None

    # ---- classification ------------------------------------------------
    def is_fixed_width(self) -> bool:
        return self.root in _FIXED_NUMPY

    def is_string_like(self) -> bool:
        return self.root in (
            TypeRoot.CHAR,
            TypeRoot.VARCHAR,
            TypeRoot.BINARY,
            TypeRoot.VARBINARY,
        )

    def is_numeric(self) -> bool:
        return self.root in (
            TypeRoot.TINYINT,
            TypeRoot.SMALLINT,
            TypeRoot.INT,
            TypeRoot.BIGINT,
            TypeRoot.FLOAT,
            TypeRoot.DOUBLE,
            TypeRoot.DECIMAL,
        )

    def numpy_dtype(self) -> np.dtype:
        """Physical host dtype. Variable-width types use object arrays."""
        if self.root in _FIXED_NUMPY:
            return _FIXED_NUMPY[self.root]
        return np.dtype(object)

    def with_nullable(self, nullable: bool) -> "DataType":
        return replace(self, nullable=nullable)

    def copy(self) -> "DataType":
        return self

    # ---- serialization -------------------------------------------------
    def serialize(self) -> Any:
        """Compact string form, e.g. "INT NOT NULL", "VARCHAR(10)", "DECIMAL(10,2)"."""
        r = self.root
        if r in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
            if self.length is None or self.length == _MAX_LEN:
                base = {"VARCHAR": "STRING", "VARBINARY": "BYTES"}.get(r.value, f"{r.value}({_MAX_LEN})")
            else:
                base = f"{r.value}({self.length})"
        elif r == TypeRoot.DECIMAL:
            base = f"DECIMAL({self.precision or 18},{self.scale or 0})"
        elif r in (TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ):
            p = 6 if self.precision is None else self.precision
            base = f"{r.value}({p})"
        else:
            base = r.value
        return base if self.nullable else base + " NOT NULL"

    def __str__(self) -> str:  # pragma: no cover - debugging aid
        s = self.serialize()
        return s if isinstance(s, str) else json.dumps(s)


@dataclass(frozen=True)
class ArrayType(DataType):
    element: DataType = None  # type: ignore[assignment]

    def __init__(self, element: DataType, nullable: bool = True):
        object.__setattr__(self, "root", TypeRoot.ARRAY)
        object.__setattr__(self, "nullable", nullable)
        object.__setattr__(self, "length", None)
        object.__setattr__(self, "precision", None)
        object.__setattr__(self, "scale", None)
        object.__setattr__(self, "element", element)

    def serialize(self) -> Any:
        return {"type": "ARRAY" if self.nullable else "ARRAY NOT NULL", "element": self.element.serialize()}


@dataclass(frozen=True)
class MapType(DataType):
    key: DataType = None  # type: ignore[assignment]
    value: DataType = None  # type: ignore[assignment]

    def __init__(self, key: DataType, value: DataType, nullable: bool = True):
        object.__setattr__(self, "root", TypeRoot.MAP)
        object.__setattr__(self, "nullable", nullable)
        object.__setattr__(self, "length", None)
        object.__setattr__(self, "precision", None)
        object.__setattr__(self, "scale", None)
        object.__setattr__(self, "key", key)
        object.__setattr__(self, "value", value)

    def serialize(self) -> Any:
        return {
            "type": "MAP" if self.nullable else "MAP NOT NULL",
            "key": self.key.serialize(),
            "value": self.value.serialize(),
        }


# ---- convenience constructors ------------------------------------------

def TINYINT(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.TINYINT, nullable)


def SMALLINT(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.SMALLINT, nullable)


def INT(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.INT, nullable)


def BIGINT(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.BIGINT, nullable)


def FLOAT(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.FLOAT, nullable)


def DOUBLE(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.DOUBLE, nullable)


def BOOLEAN(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.BOOLEAN, nullable)


def CHAR(length: int, nullable: bool = True) -> DataType:
    return DataType(TypeRoot.CHAR, nullable, length=length)


def VARCHAR(length: int, nullable: bool = True) -> DataType:
    return DataType(TypeRoot.VARCHAR, nullable, length=length)


def STRING(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.VARCHAR, nullable, length=_MAX_LEN)


def BYTES(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.VARBINARY, nullable, length=_MAX_LEN)


def DATE(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.DATE, nullable)


def TIME(nullable: bool = True) -> DataType:
    return DataType(TypeRoot.TIME, nullable)


def TIMESTAMP(precision: int = 6, nullable: bool = True) -> DataType:
    return DataType(TypeRoot.TIMESTAMP, nullable, precision=precision)


def DECIMAL(precision: int = 18, scale: int = 0, nullable: bool = True) -> DataType:
    if precision > 18:
        raise ValueError("paimon-tpu supports DECIMAL precision <= 18 (unscaled int64)")
    return DataType(TypeRoot.DECIMAL, nullable, precision=precision, scale=scale)


_TYPE_RE = re.compile(r"^([A-Z_]+)(?:\((\d+)(?:,\s*(\d+))?\))?( NOT NULL)?$")


def parse_type(s: Any) -> DataType:
    """Inverse of DataType.serialize()."""
    if isinstance(s, dict):
        t = s["type"]
        nullable = not t.endswith("NOT NULL")
        base = t.replace(" NOT NULL", "")
        if base == "ARRAY":
            return ArrayType(parse_type(s["element"]), nullable)
        if base == "MAP":
            return MapType(parse_type(s["key"]), parse_type(s["value"]), nullable)
        if base == "ROW":
            return RowType([DataField.from_dict(f) for f in s["fields"]], nullable)
        raise ValueError(f"unknown structured type {t}")
    m = _TYPE_RE.match(s.strip())
    if not m:
        raise ValueError(f"cannot parse type {s!r}")
    name, p1, p2, notnull = m.groups()
    nullable = notnull is None
    if name == "STRING":
        return STRING(nullable)
    if name == "BYTES":
        return BYTES(nullable)
    root = TypeRoot(name)
    if root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
        return DataType(root, nullable, length=int(p1) if p1 else _MAX_LEN)
    if root == TypeRoot.DECIMAL:
        return DataType(root, nullable, precision=int(p1 or 18), scale=int(p2 or 0))
    if root in (TypeRoot.TIMESTAMP, TypeRoot.TIMESTAMP_LTZ):
        return DataType(root, nullable, precision=int(p1) if p1 else 6)
    return DataType(root, nullable)


@dataclass(frozen=True)
class DataField:
    """A named, id-carrying field. Field ids — not names or positions — are the
    durable identity used for schema evolution (reference:
    types/DataField.java, schema/SchemaEvolutionUtil.java:54)."""

    id: int
    name: str
    type: DataType
    description: str | None = None

    def to_dict(self) -> dict:
        d = {"id": self.id, "name": self.name, "type": self.type.serialize()}
        if self.description:
            d["description"] = self.description
        return d

    @staticmethod
    def from_dict(d: dict) -> "DataField":
        return DataField(d["id"], d["name"], parse_type(d["type"]), d.get("description"))


class RowType(DataType):
    """A sequence of DataFields; the schema of every row/batch."""

    def __init__(self, fields: Iterable[DataField], nullable: bool = True):
        object.__setattr__(self, "root", TypeRoot.ROW)
        object.__setattr__(self, "nullable", nullable)
        object.__setattr__(self, "length", None)
        object.__setattr__(self, "precision", None)
        object.__setattr__(self, "scale", None)
        object.__setattr__(self, "fields", tuple(fields))
        names = [f.name for f in self.fields]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate field names in {names}")
        object.__setattr__(self, "_index", {f.name: i for i, f in enumerate(self.fields)})

    fields: tuple[DataField, ...]
    _index: dict

    # ---- construction helpers -----------------------------------------
    @staticmethod
    def of(*spec: tuple[str, DataType]) -> "RowType":
        """RowType.of(("k", INT()), ("v", STRING())) with ids 0..n-1."""
        return RowType([DataField(i, n, t) for i, (n, t) in enumerate(spec)])

    # ---- accessors -----------------------------------------------------
    @property
    def field_names(self) -> list[str]:
        return [f.name for f in self.fields]

    @property
    def field_types(self) -> list[DataType]:
        return [f.type for f in self.fields]

    def __len__(self) -> int:
        return len(self.fields)

    def field(self, name: str) -> DataField:
        return self.fields[self._index[name]]

    def field_index(self, name: str) -> int:
        return self._index[name]

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def highest_field_id(self) -> int:
        return max((f.id for f in self.fields), default=-1)

    def project(self, names: Iterable[str]) -> "RowType":
        return RowType([self.field(n) for n in names], self.nullable)

    # ---- serialization -------------------------------------------------
    def serialize(self) -> Any:
        return {
            "type": "ROW" if self.nullable else "ROW NOT NULL",
            "fields": [f.to_dict() for f in self.fields],
        }

    def to_json(self) -> str:
        return json.dumps(self.serialize(), indent=2)

    @staticmethod
    def from_json(s: str | dict) -> "RowType":
        d = json.loads(s) if isinstance(s, str) else s
        t = parse_type(d)
        assert isinstance(t, RowType)
        return t

    def __eq__(self, other: object) -> bool:
        return isinstance(other, RowType) and self.fields == other.fields

    def __hash__(self) -> int:
        return hash(self.fields)


class RowKind(enum.IntEnum):
    """Changelog row kinds (reference: types/RowKind.java). Stored as uint8
    vectors; byte values match the reference's ordinal for changelog parity."""

    INSERT = 0  # +I
    UPDATE_BEFORE = 1  # -U
    UPDATE_AFTER = 2  # +U
    DELETE = 3  # -D

    @property
    def short_string(self) -> str:
        return ("+I", "-U", "+U", "-D")[int(self)]

    @property
    def is_add(self) -> bool:
        """Rows that accumulate state (+I/+U) vs retract (-U/-D)."""
        return self in (RowKind.INSERT, RowKind.UPDATE_AFTER)

    @staticmethod
    def from_short_string(s: str) -> "RowKind":
        return {"+I": RowKind.INSERT, "-U": RowKind.UPDATE_BEFORE, "+U": RowKind.UPDATE_AFTER, "-D": RowKind.DELETE}[s]
