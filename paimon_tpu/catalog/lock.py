"""Catalog locks: commit mutual exclusion where rename is not atomic.

Parity: /root/reference/paimon-core/.../catalog/CatalogLock.java (SPI) and
the jdbc/hive lock dialects (jdbc/JdbcDistributedLockDialect.java) — on
object stores without atomic rename the snapshot CAS degrades, so commits
run under an external lock. The filesystem implementation here claims an
O_EXCL lock file (with a stale-TTL takeover for crashed holders), which is
exactly the primitive the reference's dialects emulate over JDBC/Hive.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager

__all__ = ["CatalogLock", "FileBasedCatalogLock"]


class CatalogLock:
    """SPI: mutual exclusion for one table's commits."""

    @contextmanager
    def lock(self, database: str, table: str):  # pragma: no cover - interface
        raise NotImplementedError
        yield


class FileBasedCatalogLock(CatalogLock):
    """Lock file next to the table metadata: created O_EXCL (one winner),
    holder id + timestamp inside, stale locks (crashed holders) taken over
    after `stale_ttl` seconds."""

    def __init__(self, file_io, table_path: str, timeout: float = 60.0, stale_ttl: float = 300.0):
        self.file_io = file_io
        self.table_path = table_path
        self.timeout = timeout
        self.stale_ttl = stale_ttl
        self.holder = uuid.uuid4().hex

    def _path(self) -> str:
        return f"{self.table_path}/.catalog-lock"

    @contextmanager
    def lock(self, database: str = "", table: str = ""):
        path = self._path()
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                payload = f"{self.holder} {time.time()}".encode()
                # write_bytes without overwrite is O_EXCL on LocalFileIO
                self.file_io.write_bytes(path, payload, overwrite=False)
                break
            except FileExistsError:
                try:
                    raw = self.file_io.read_bytes(path).decode()
                    _, ts = raw.split()
                    if time.time() - float(ts) > self.stale_ttl:
                        # crashed holder: take over by ATOMIC rename — only
                        # one waiter wins the tombstone, so a racer can never
                        # delete a FRESH lock another waiter just created
                        tomb = f"{path}.stale-{uuid.uuid4().hex}"
                        try:
                            if self.file_io.rename(path, tomb):
                                self.file_io.delete(tomb)
                        except Exception:
                            pass
                        continue
                except Exception:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"could not acquire catalog lock {path}")
                time.sleep(0.05)
        # heartbeat: refresh our timestamp so a long commit is never mistaken
        # for a crashed holder and stolen mid-flight
        stop = threading.Event()

        def beat():
            interval = self.stale_ttl / 3
            while not stop.wait(interval):
                try:
                    raw = self.file_io.read_bytes(path).decode()
                    if raw.split()[0] != self.holder:
                        return  # lost the lock (TTL takeover): stop touching it
                    self.file_io.write_bytes(path, f"{self.holder} {time.time()}".encode(), overwrite=True)
                    interval = self.stale_ttl / 3
                except Exception:
                    # transient IO hiccup: keep beating (retry sooner), else a
                    # waiter would sweep the "stale" lock while we still hold
                    # the critical section.  A real takeover is detected above
                    # by the holder mismatch once reads succeed again.
                    interval = min(1.0, self.stale_ttl / 10)

        hb = threading.Thread(target=beat, daemon=True)
        hb.start()
        try:
            yield
        finally:
            stop.set()
            hb.join(timeout=1.0)
            # release only OUR lock: after a stale-TTL takeover the file may
            # belong to another holder now
            try:
                raw = self.file_io.read_bytes(path).decode()
                if raw.split()[0] == self.holder:
                    self.file_io.delete(path)
            except Exception:
                pass
