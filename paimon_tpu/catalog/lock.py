"""Catalog locks: commit mutual exclusion where rename is not atomic.

Parity: /root/reference/paimon-core/.../catalog/CatalogLock.java (SPI) and
the jdbc/hive lock dialects (jdbc/JdbcDistributedLockDialect.java) — on
object stores without atomic rename the snapshot CAS degrades, so commits
run under an external lock. The filesystem implementation here claims an
O_EXCL lock file (with a stale-TTL takeover for crashed holders), which is
exactly the primitive the reference's dialects emulate over JDBC/Hive.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from contextlib import contextmanager

__all__ = ["CatalogLock", "FileBasedCatalogLock"]


class CatalogLock:
    """SPI: mutual exclusion for one table's commits."""

    @contextmanager
    def lock(self, database: str, table: str):  # pragma: no cover - interface
        raise NotImplementedError
        yield


class FileBasedCatalogLock(CatalogLock):
    """Lock file next to the table metadata: created O_EXCL (one winner),
    holder id + timestamp inside, stale locks (crashed holders) taken over
    after `stale_ttl` seconds."""

    def __init__(self, file_io, table_path: str, timeout: float = 60.0, stale_ttl: float = 300.0):
        self.file_io = file_io
        self.table_path = table_path
        self.timeout = timeout
        self.stale_ttl = stale_ttl
        self.holder = uuid.uuid4().hex

    def _path(self) -> str:
        return f"{self.table_path}/.catalog-lock"

    @contextmanager
    def lock(self, database: str = "", table: str = ""):
        path = self._path()
        deadline = time.monotonic() + self.timeout
        while True:
            try:
                payload = f"{self.holder} {time.time()}".encode()
                # write_bytes without overwrite is O_EXCL on LocalFileIO
                self.file_io.write_bytes(path, payload, overwrite=False)
                break
            except FileExistsError:
                try:
                    raw = self.file_io.read_bytes(path)
                    _, ts = raw.decode().split()
                    if time.time() - float(ts) > self.stale_ttl:
                        self._sweep_stale(path, raw)
                        continue
                except Exception:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"could not acquire catalog lock {path}")
                time.sleep(0.05)
        # heartbeat: refresh our timestamp so a long commit is never mistaken
        # for a crashed holder and stolen mid-flight
        stop = threading.Event()

        def beat():
            interval = self.stale_ttl / 3
            while not stop.wait(interval):
                try:
                    raw = self.file_io.read_bytes(path).decode()
                    if raw.split()[0] != self.holder:
                        return  # lost the lock (TTL takeover): stop touching it
                    self.file_io.write_bytes(path, f"{self.holder} {time.time()}".encode(), overwrite=True)
                    interval = self.stale_ttl / 3
                except Exception:
                    # transient IO hiccup: keep beating (retry sooner), else a
                    # waiter would sweep the "stale" lock while we still hold
                    # the critical section.  A real takeover is detected above
                    # by the holder mismatch once reads succeed again.
                    interval = min(1.0, self.stale_ttl / 10)

        hb = threading.Thread(target=beat, daemon=True)
        hb.start()
        try:
            yield
        finally:
            stop.set()
            hb.join(timeout=1.0)
            # release only OUR lock: after a stale-TTL takeover the file may
            # belong to another holder now
            try:
                raw = self.file_io.read_bytes(path).decode()
                if raw.split()[0] == self.holder:
                    self.file_io.delete(path)
            except Exception:
                pass

    def _sweep_stale(self, path: str, raw: bytes) -> None:
        """Remove a crashed holder's lock with exactly-one-deleter semantics.

        The sweep right is a CAS on a tombstone keyed by the stale lock's
        CONTENT (holder uuid + timestamp — unique per incarnation): whoever
        exclusively creates the tombstone is the only process allowed to
        delete that incarnation, and it re-checks the content first.  A racer
        can therefore never delete a FRESH lock another waiter just created.
        (The previous design renamed the lock away, but rename is
        copy+delete on object stores — the delete half could land on a fresh
        lock.)  A sweeper that crashes mid-sweep leaves its tombstone; other
        waiters clear tombstones older than stale_ttl."""
        import hashlib

        tomb = f"{path}.sweep-{hashlib.sha1(raw).hexdigest()[:16]}"
        if self.file_io.try_atomic_write(tomb, f"{time.time()}".encode()):
            try:
                if self.file_io.read_bytes(path) == raw:
                    self.file_io.delete(path)
            except Exception:
                pass
            finally:
                try:
                    self.file_io.delete(tomb)
                except Exception:
                    pass
        else:
            # another waiter owns this sweep; clear its tombstone if it
            # crashed mid-sweep so the takeover can eventually proceed
            try:
                t = float(self.file_io.read_bytes(tomb).decode())
                if time.time() - t > self.stale_ttl:
                    self.file_io.delete(tomb)
            except Exception:
                pass
