"""Catalog-scope system tables + lineage store.

Parity: /root/reference/paimon-core/.../table/system/SystemTableLoader.java
loadGlobal — ALL_TABLE_OPTIONS, CATALOG_OPTIONS, and the four lineage tables
(SourceTableLineageTable/SinkTableLineageTable/SourceDataLineageTable/
SinkDataLineageTable backed by a LineageMeta SPI). The reference ships the
table surface but no default LineageMeta implementation; here the catalog
carries a filesystem-backed lineage store (jsonl under warehouse/.lineage)
so the tables are actually queryable.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

from ..data.batch import ColumnBatch
from ..types import BIGINT, STRING, RowType
from ..utils import now_millis

if TYPE_CHECKING:
    from . import FileSystemCatalog

__all__ = ["FsLineageMeta", "global_system_table", "GLOBAL_SYSTEM_TABLES"]


class FsLineageMeta:
    """Filesystem lineage store (the LineageMeta SPI analog): append-only
    jsonl of table- and data-level lineage entries under the warehouse."""

    def __init__(self, catalog: "FileSystemCatalog"):
        self.file_io = catalog.file_io
        self.dir = f"{catalog.warehouse}/.lineage"

    def _append(self, name: str, entry: dict) -> None:
        # one O_EXCL file per entry: concurrent jobs cannot lose each other's
        # entries, and appends stay O(1)
        import uuid

        d = f"{self.dir}/{name}"
        self.file_io.mkdirs(d)
        self.file_io.write_bytes(f"{d}/e-{uuid.uuid4().hex}.json", json.dumps(entry).encode())

    def _read(self, name: str) -> list[dict]:
        d = f"{self.dir}/{name}"
        out = []
        for st in self.file_io.list_status(d):
            if not st.is_dir and st.path.endswith(".json"):
                out.append(json.loads(self.file_io.read_bytes(st.path)))
        out.sort(key=lambda e: e.get("create_time", 0))
        return out

    def save_source_table_lineage(self, job: str, table: str) -> None:
        self._append("source_table", {"database_name": table.split(".")[0], "table_name": table.split(".")[-1], "job_name": job, "create_time": now_millis()})

    def save_sink_table_lineage(self, job: str, table: str) -> None:
        self._append("sink_table", {"database_name": table.split(".")[0], "table_name": table.split(".")[-1], "job_name": job, "create_time": now_millis()})

    def save_source_data_lineage(self, job: str, table: str, barrier_id: int, snapshot_id: int) -> None:
        self._append("source_data", {"database_name": table.split(".")[0], "table_name": table.split(".")[-1], "job_name": job, "barrier_id": barrier_id, "snapshot_id": snapshot_id, "create_time": now_millis()})

    def save_sink_data_lineage(self, job: str, table: str, barrier_id: int, snapshot_id: int) -> None:
        self._append("sink_data", {"database_name": table.split(".")[0], "table_name": table.split(".")[-1], "job_name": job, "barrier_id": barrier_id, "snapshot_id": snapshot_id, "create_time": now_millis()})

    def table_lineages(self, kind: str) -> list[dict]:
        return self._read(f"{kind}_table")

    def data_lineages(self, kind: str) -> list[dict]:
        return self._read(f"{kind}_data")


from ..table.system import _StaticTable


def _all_table_options(catalog: "FileSystemCatalog") -> _StaticTable:
    schema = RowType.of(
        ("database_name", STRING(False)),
        ("table_name", STRING(False)),
        ("key", STRING(False)),
        ("value", STRING(False)),
    )
    rows = []
    for db in catalog.list_databases():
        for name in catalog.list_tables(db):
            t = catalog.get_table(f"{db}.{name}")
            for k, v in sorted(t.schema.options.items()):
                rows.append((db, name, k, str(v)))
    return _StaticTable("all_table_options", ColumnBatch.from_pylist(schema, rows))


def _catalog_options(catalog: "FileSystemCatalog") -> _StaticTable:
    schema = RowType.of(("key", STRING(False)), ("value", STRING(False)))
    rows = [("warehouse", catalog.warehouse)]
    return _StaticTable("catalog_options", ColumnBatch.from_pylist(schema, rows))


_TABLE_LINEAGE_SCHEMA = RowType.of(
    ("database_name", STRING(False)),
    ("table_name", STRING(False)),
    ("job_name", STRING(False)),
    ("create_time", BIGINT(False)),
)
_DATA_LINEAGE_SCHEMA = RowType.of(
    ("database_name", STRING(False)),
    ("table_name", STRING(False)),
    ("job_name", STRING(False)),
    ("barrier_id", BIGINT(False)),
    ("snapshot_id", BIGINT(False)),
    ("create_time", BIGINT(False)),
)


def _table_lineage(kind: str):
    def load(catalog: "FileSystemCatalog") -> _StaticTable:
        rows = [
            (e["database_name"], e["table_name"], e["job_name"], e["create_time"])
            for e in FsLineageMeta(catalog).table_lineages(kind)
        ]
        return _StaticTable(f"{kind}_table_lineage", ColumnBatch.from_pylist(_TABLE_LINEAGE_SCHEMA, rows))

    return load


def _data_lineage(kind: str):
    def load(catalog: "FileSystemCatalog") -> _StaticTable:
        rows = [
            (e["database_name"], e["table_name"], e["job_name"], e["barrier_id"], e["snapshot_id"], e["create_time"])
            for e in FsLineageMeta(catalog).data_lineages(kind)
        ]
        return _StaticTable(f"{kind}_data_lineage", ColumnBatch.from_pylist(_DATA_LINEAGE_SCHEMA, rows))

    return load


GLOBAL_SYSTEM_TABLES = {
    "all_table_options": _all_table_options,
    "catalog_options": _catalog_options,
    "source_table_lineage": _table_lineage("source"),
    "sink_table_lineage": _table_lineage("sink"),
    "source_data_lineage": _data_lineage("source"),
    "sink_data_lineage": _data_lineage("sink"),
}


def global_system_table(catalog: "FileSystemCatalog", name: str):
    try:
        fn = GLOBAL_SYSTEM_TABLES[name]
    except KeyError:
        raise ValueError(
            f"unknown global system table {name!r}; known: {sorted(GLOBAL_SYSTEM_TABLES)}"
        ) from None
    return fn(catalog)
