"""File-based privilege system wrapping a catalog.

Parity: /root/reference/paimon-core/.../privilege/ — a file-based RBAC layer
(PrivilegedCatalog / PrivilegeManager): users, password check, per-object
privileges (SELECT/INSERT/ADMIN), enforced by wrapping catalog and table
operations.
"""

from __future__ import annotations

import hashlib
from typing import Sequence

from ..fs import get_file_io
from ..utils import dumps, loads
from . import Catalog, FileSystemCatalog, Identifier

__all__ = ["PrivilegedCatalog", "PrivilegeManager", "AccessDeniedError"]

SELECT = "SELECT"
INSERT = "INSERT"
ADMIN = "ADMIN"


class AccessDeniedError(PermissionError):
    pass


class PrivilegeManager:
    ROOT = "root"

    def __init__(self, warehouse: str):
        self.file_io = get_file_io(warehouse)
        self.path = f"{warehouse}/.privilege/meta.json"

    def _load(self) -> dict:
        try:
            return loads(self.file_io.read_bytes(self.path))
        except Exception:
            return {"users": {}, "grants": {}}

    def _save(self, d: dict) -> None:
        self.file_io.try_overwrite(self.path, dumps(d).encode())

    @staticmethod
    def _hash(password: str) -> str:
        return hashlib.sha256(password.encode()).hexdigest()

    def initialized(self) -> bool:
        return self.file_io.exists(self.path)

    def init(self, root_password: str) -> None:
        if self.initialized():
            raise ValueError("privileges already initialized")
        self._save({"users": {self.ROOT: self._hash(root_password)}, "grants": {}})

    def create_user(self, user: str, password: str) -> None:
        d = self._load()
        if user in d["users"]:
            raise ValueError(f"user {user} exists")
        d["users"][user] = self._hash(password)
        self._save(d)

    def drop_user(self, user: str) -> None:
        d = self._load()
        d["users"].pop(user, None)
        d["grants"].pop(user, None)
        self._save(d)

    def authenticate(self, user: str, password: str) -> bool:
        d = self._load()
        return d["users"].get(user) == self._hash(password)

    def grant(self, user: str, obj: str, privilege: str) -> None:
        d = self._load()
        if user not in d["users"]:
            raise ValueError(f"no user {user}")
        d["grants"].setdefault(user, {}).setdefault(obj, [])
        if privilege not in d["grants"][user][obj]:
            d["grants"][user][obj].append(privilege)
        self._save(d)

    def revoke(self, user: str, obj: str, privilege: str) -> None:
        d = self._load()
        try:
            d["grants"][user][obj].remove(privilege)
        except (KeyError, ValueError):
            pass
        self._save(d)

    def has(self, user: str, obj: str, privilege: str) -> bool:
        if user == self.ROOT:
            return True
        grants = self._load()["grants"].get(user, {})
        # object hierarchy: "db.table" inherits from "db" inherits from "*"
        for scope in (obj, obj.split(".")[0], "*"):
            privs = grants.get(scope, ())
            if privilege in privs or ADMIN in privs:
                return True
        return False


class PrivilegedCatalog(Catalog):
    """Catalog wrapper enforcing privileges (reference PrivilegedCatalog)."""

    def __init__(self, warehouse: str, user: str, password: str):
        self.manager = PrivilegeManager(warehouse)
        if self.manager.initialized() and not self.manager.authenticate(user, password):
            raise AccessDeniedError(f"authentication failed for {user!r}")
        self.user = user
        self._inner = FileSystemCatalog(warehouse, commit_user=user)

    def _check(self, obj: str, privilege: str) -> None:
        if self.manager.initialized() and not self.manager.has(self.user, obj, privilege):
            raise AccessDeniedError(f"user {self.user!r} lacks {privilege} on {obj!r}")

    # reads ---------------------------------------------------------------
    def list_databases(self):
        return self._inner.list_databases()

    def list_tables(self, database: str):
        return self._inner.list_tables(database)

    def get_table(self, identifier):
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        base = ident.table.split(self._inner.SYSTEM_SEP)[0]
        self._check(f"{ident.database}.{base}", SELECT)
        return self._inner.get_table(identifier)

    # writes --------------------------------------------------------------
    def create_database(self, name: str, ignore_if_exists: bool = True):
        self._check(name, ADMIN)
        return self._inner.create_database(name, ignore_if_exists)

    def drop_database(self, name: str, cascade: bool = False):
        self._check(name, ADMIN)
        return self._inner.drop_database(name, cascade)

    def create_table(self, identifier, row_type, **kw):
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        self._check(ident.database, ADMIN)
        return self._inner.create_table(identifier, row_type, **kw)

    def drop_table(self, identifier):
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        self._check(f"{ident.database}.{ident.table}", ADMIN)
        return self._inner.drop_table(identifier)

    def writable_table(self, identifier):
        """get_table + INSERT check (writes go through the returned table)."""
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        self._check(f"{ident.database}.{ident.table}", INSERT)
        return self._inner.get_table(identifier)
