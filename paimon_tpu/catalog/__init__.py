"""Catalog: databases and tables on a warehouse directory.

Parity: /root/reference/paimon-core/.../catalog/ — Catalog SPI +
FileSystemCatalog (warehouse layout `warehouse/db.db/table`), create/drop/
list/rename, system-table routing via `table$system`.
"""

from __future__ import annotations

from typing import Sequence

from ..core.schema import SchemaManager, TableSchema
from ..fs import FileIO, get_file_io
from ..table import FileStoreTable, Table
from ..types import RowType

__all__ = ["Catalog", "FileSystemCatalog", "Identifier"]


class Identifier:
    def __init__(self, database: str, table: str):
        self.database = database
        self.table = table

    @staticmethod
    def parse(full: str) -> "Identifier":
        db, _, tbl = full.partition(".")
        if not tbl:
            raise ValueError(f"expected db.table, got {full!r}")
        return Identifier(db, tbl)

    @property
    def full_name(self) -> str:
        return f"{self.database}.{self.table}"

    def __repr__(self):
        return self.full_name


class Catalog:
    def list_databases(self) -> list[str]:
        raise NotImplementedError

    def create_database(self, name: str, ignore_if_exists: bool = True) -> None:
        raise NotImplementedError

    def drop_database(self, name: str, cascade: bool = False) -> None:
        raise NotImplementedError

    def list_tables(self, database: str) -> list[str]:
        raise NotImplementedError

    def create_table(self, identifier, schema, **kw) -> "Table":
        raise NotImplementedError

    def get_table(self, identifier) -> "Table":
        raise NotImplementedError

    def drop_table(self, identifier) -> None:
        raise NotImplementedError


class FileSystemCatalog(Catalog):
    DB_SUFFIX = ".db"
    SYSTEM_SEP = "$"

    def __init__(self, warehouse: str, commit_user: str = "anonymous"):
        self.warehouse = warehouse.rstrip("/")
        self.file_io: FileIO = get_file_io(warehouse)
        self.commit_user = commit_user
        # catalog metadata probes (get_table schema reads, listings) run
        # BEFORE any table's store exists to supply its fs.retry budget —
        # give them the default budget so a transient store blip resolves
        # a table instead of failing the lookup. Tables themselves still
        # receive the RAW io: the store re-wraps per its own options.
        from ..options import CoreOptions
        from ..resilience.fileio import wrap_file_io

        self._meta_io: FileIO = wrap_file_io(self.file_io, CoreOptions())

    # ---- databases -----------------------------------------------------
    def _db_path(self, name: str) -> str:
        return f"{self.warehouse}/{name}{self.DB_SUFFIX}"

    def list_databases(self) -> list[str]:
        out = []
        for st in self._meta_io.list_status(self.warehouse):
            base = st.path.rsplit("/", 1)[-1]
            if st.is_dir and base.endswith(self.DB_SUFFIX):
                out.append(base[: -len(self.DB_SUFFIX)])
        return sorted(out)

    def create_database(self, name: str, ignore_if_exists: bool = True) -> None:
        if name == "sys":
            raise ValueError("'sys' is reserved for catalog system tables")
        path = self._db_path(name)
        if self.file_io.exists(path):
            if not ignore_if_exists:
                raise ValueError(f"database {name} exists")
            return
        self.file_io.mkdirs(path)

    def drop_database(self, name: str, cascade: bool = False) -> None:
        if not cascade and self.list_tables(name):
            raise ValueError(f"database {name} is not empty")
        self.file_io.delete(self._db_path(name), recursive=True)
        from ..utils.cache import invalidate_table_path

        invalidate_table_path(self._db_path(name))

    # ---- tables --------------------------------------------------------
    def table_path(self, identifier: "Identifier | str") -> str:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        return f"{self._db_path(ident.database)}/{ident.table}"

    def list_tables(self, database: str) -> list[str]:
        out = []
        for st in self._meta_io.list_status(self._db_path(database)):
            if st.is_dir and self._meta_io.exists(f"{st.path}/schema"):
                out.append(st.path.rsplit("/", 1)[-1])
        return sorted(out)

    def create_table(
        self,
        identifier: "Identifier | str",
        row_type: RowType,
        partition_keys: Sequence[str] = (),
        primary_keys: Sequence[str] = (),
        options: dict | None = None,
        ignore_if_exists: bool = False,
    ) -> FileStoreTable:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        self.create_database(ident.database)  # raises for the reserved 'sys'
        path = self.table_path(ident)
        sm = SchemaManager(self.file_io, path)
        if sm.latest() is not None and not ignore_if_exists:
            raise ValueError(f"table {ident} exists")
        # reference CoreOptions.PRIMARY_KEY / PARTITION: constraints defined
        # via options when the creating surface cannot express them — and
        # rejected when BOTH forms are given
        options = dict(options or {})
        for opt_key, arg, label in (
            ("primary-key", primary_keys, "primary key"),
            ("partition", partition_keys, "partition"),
        ):
            if opt_key in options:
                from_opt = [c.strip() for c in options.pop(opt_key).split(",") if c.strip()]
                if arg:
                    raise ValueError(
                        f"cannot define {label} both explicitly and via the {opt_key!r} option"
                    )
                if opt_key == "primary-key":
                    primary_keys = from_opt
                else:
                    partition_keys = from_opt
        schema = sm.create_table(row_type, partition_keys, primary_keys, options)
        return FileStoreTable(self.file_io, path, schema, self.commit_user)

    def system_table(self, name: str):
        """Catalog-scope system tables: sys.all_table_options,
        sys.catalog_options, lineage x4 (reference SystemTableLoader
        loadGlobal)."""
        from .globals import global_system_table

        return global_system_table(self, name)

    def lineage_meta(self):
        """The catalog's lineage store (reference LineageMeta SPI)."""
        from .globals import FsLineageMeta

        return FsLineageMeta(self)

    def get_table(self, identifier: "Identifier | str") -> Table:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        if ident.database == "sys":
            return self.system_table(ident.table)
        if self.SYSTEM_SEP in ident.table:
            base, _, sys_name = ident.table.partition(self.SYSTEM_SEP)
            data_table = self.get_table(Identifier(ident.database, base))
            from ..table.system import system_table

            return system_table(data_table, sys_name)
        path = self.table_path(ident)
        sm = SchemaManager(self._meta_io, path)
        schema = sm.latest()
        if schema is None:
            raise FileNotFoundError(f"table {ident} does not exist")
        return FileStoreTable(self.file_io, path, schema, self.commit_user)

    def drop_table(self, identifier: "Identifier | str") -> None:
        self.file_io.delete(self.table_path(identifier), recursive=True)
        # a recreated table at the same path re-mints snapshot ids
        from ..utils.cache import invalidate_table_path

        invalidate_table_path(self.table_path(identifier))

    def rename_table(self, src: "Identifier | str", dst: "Identifier | str") -> None:
        ok = self.file_io.rename(self.table_path(src), self.table_path(dst))
        if not ok:
            raise ValueError(f"cannot rename {src} -> {dst} (destination exists)")
        from ..utils.cache import invalidate_table_path

        invalidate_table_path(self.table_path(src))

    def alter_table(self, identifier: "Identifier | str", *changes: dict) -> TableSchema:
        path = self.table_path(identifier)
        return SchemaManager(self.file_io, path).commit_changes(*changes)
