"""SQL-database-backed catalog (the JdbcCatalog analog, on sqlite).

Parity: /root/reference/paimon-core/.../jdbc/JdbcCatalog.java — table
metadata lives in relational tables instead of warehouse directory listing,
and the database doubles as the distributed lock dialect
(jdbc/JdbcDistributedLockDialect.java: acquire = INSERT into a lock table
with a unique key, release = DELETE, stale locks expire by timestamp). The
embedded engine here is sqlite (stdlib); the schema mirrors the reference's
databases/tables/locks layout, and table DATA stays on the warehouse
filesystem exactly as with the filesystem catalog — only the catalog plane
moves into SQL.
"""

from __future__ import annotations

import sqlite3
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Sequence

from ..core.schema import SchemaManager
from ..fs import FileIO, get_file_io
from ..table import FileStoreTable, Table
from ..types import RowType
from . import Catalog, Identifier
from .lock import CatalogLock

__all__ = ["JdbcCatalog", "JdbcCatalogLock"]

# one definition, shared by the catalog schema and standalone locks
_LOCK_TABLE_DDL = """
CREATE TABLE IF NOT EXISTS paimon_distributed_locks (
    lock_id TEXT PRIMARY KEY,
    holder TEXT NOT NULL,
    acquired_at REAL NOT NULL
);
"""

_SCHEMA = (
    """
CREATE TABLE IF NOT EXISTS paimon_databases (
    name TEXT PRIMARY KEY
);
CREATE TABLE IF NOT EXISTS paimon_tables (
    database_name TEXT NOT NULL,
    table_name TEXT NOT NULL,
    location TEXT NOT NULL,
    PRIMARY KEY (database_name, table_name)
);
"""
    + _LOCK_TABLE_DDL
)


class JdbcCatalog(Catalog):
    def __init__(self, db_path: str, warehouse: str, commit_user: str = "anonymous"):
        self.db_path = db_path
        self.warehouse = warehouse.rstrip("/")
        self.file_io: FileIO = get_file_io(warehouse)
        self.commit_user = commit_user
        with self._conn() as c:
            c.executescript(_SCHEMA)

    @contextmanager
    def _conn(self):
        # one short-lived connection per operation; closed (not just
        # committed) so per-op/per-heartbeat connections cannot leak fds
        c = sqlite3.connect(self.db_path, timeout=30.0)
        c.execute("PRAGMA busy_timeout = 30000")
        try:
            with c:
                yield c
        finally:
            c.close()

    # ---- databases -----------------------------------------------------
    def list_databases(self) -> list[str]:
        with self._conn() as c:
            return sorted(r[0] for r in c.execute("SELECT name FROM paimon_databases"))

    def create_database(self, name: str, ignore_if_exists: bool = True) -> None:
        if name == "sys":
            raise ValueError("'sys' is reserved for catalog system tables")
        with self._conn() as c:
            try:
                c.execute("INSERT INTO paimon_databases (name) VALUES (?)", (name,))
            except sqlite3.IntegrityError:
                if not ignore_if_exists:
                    raise ValueError(f"database {name} exists") from None

    def drop_database(self, name: str, cascade: bool = False) -> None:
        tables = self.list_tables(name)
        if not cascade and tables:
            raise ValueError(f"database {name} is not empty")
        # drop the DATA too — a later create_table with the same name must
        # get a fresh table, not resurrect the old schema/files
        for tbl in tables:
            self.drop_table(Identifier(name, tbl))
        with self._conn() as c:
            c.execute("DELETE FROM paimon_tables WHERE database_name = ?", (name,))
            c.execute("DELETE FROM paimon_databases WHERE name = ?", (name,))

    # ---- tables --------------------------------------------------------
    def list_tables(self, database: str) -> list[str]:
        with self._conn() as c:
            return sorted(
                r[0]
                for r in c.execute(
                    "SELECT table_name FROM paimon_tables WHERE database_name = ?", (database,)
                )
            )

    def _location(self, ident: Identifier) -> str | None:
        with self._conn() as c:
            row = c.execute(
                "SELECT location FROM paimon_tables WHERE database_name = ? AND table_name = ?",
                (ident.database, ident.table),
            ).fetchone()
        return row[0] if row else None

    def create_table(
        self,
        identifier: "Identifier | str",
        row_type: RowType,
        partition_keys: Sequence[str] = (),
        primary_keys: Sequence[str] = (),
        options: dict | None = None,
        ignore_if_exists: bool = False,
    ) -> FileStoreTable:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        self.create_database(ident.database)
        location = f"{self.warehouse}/{ident.database}.db/{ident.table}"
        with self._conn() as c:
            try:
                c.execute(
                    "INSERT INTO paimon_tables (database_name, table_name, location) VALUES (?, ?, ?)",
                    (ident.database, ident.table, location),
                )
            except sqlite3.IntegrityError:
                if not ignore_if_exists:
                    raise ValueError(f"table {ident} exists") from None
        sm = SchemaManager(self.file_io, location)
        schema = sm.latest()
        if schema is None:
            schema = sm.create_table(row_type, partition_keys, primary_keys, options)
        return FileStoreTable(self.file_io, location, schema, self.commit_user)

    def get_table(self, identifier: "Identifier | str") -> Table:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        base, sep, sys_name = ident.table.partition("$")
        location = self._location(Identifier(ident.database, base))
        if location is None:
            raise FileNotFoundError(f"table {ident.database}.{base} not in catalog")
        schema = SchemaManager(self.file_io, location).latest()
        if schema is None:
            raise FileNotFoundError(f"table {ident} has no schema at {location}")
        table = FileStoreTable(self.file_io, location, schema, self.commit_user)
        if sep:
            from ..table.system import system_table

            return system_table(table, sys_name)
        return table

    def drop_table(self, identifier: "Identifier | str") -> None:
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        location = self._location(ident)
        with self._conn() as c:
            c.execute(
                "DELETE FROM paimon_tables WHERE database_name = ? AND table_name = ?",
                (ident.database, ident.table),
            )
        if location:
            self.file_io.delete(location, recursive=True)
            from ..utils.cache import invalidate_table_path

            invalidate_table_path(location)

    def rename_table(self, src: "Identifier | str", dst: "Identifier | str") -> None:
        s = Identifier.parse(src) if isinstance(src, str) else src
        d = Identifier.parse(dst) if isinstance(dst, str) else dst
        location = self._location(s)
        if location is None:
            raise FileNotFoundError(f"table {s} not in catalog")
        with self._conn() as c:
            if c.execute(
                "SELECT 1 FROM paimon_tables WHERE database_name = ? AND table_name = ?",
                (d.database, d.table),
            ).fetchone():
                raise ValueError(f"cannot rename {s} -> {d} (destination exists)")
            # metadata-plane rename only: the reference's JdbcCatalog keeps
            # the location stable too (paths are not identity in SQL catalogs)
            c.execute(
                "UPDATE paimon_tables SET database_name = ?, table_name = ? "
                "WHERE database_name = ? AND table_name = ?",
                (d.database, d.table, s.database, s.table),
            )

    def repair(self, identifier: str | None = None) -> dict:
        """Re-sync the SQL metadata plane with the warehouse filesystem
        (reference flink/action/RepairAction + Catalog.repairCatalog).
        Identity is the STORED LOCATION, not the naming convention — a
        renamed table keeps its original path, so:
        - rows whose location no longer holds a schema are dropped;
        - on-disk schema trees whose location no catalog row references are
          registered under their conventional name;
        - databases with neither a warehouse directory nor table rows are
          dropped.
        `identifier` scopes the sync to one database ('db') or table
        ('db.t') — the reference repair procedure's single-object form.
        Returns {"registered", "removed", "removed_databases"}."""
        scope_db = scope_table = None
        if identifier:
            scope_db, _, scope_table = identifier.partition(".")
            scope_table = scope_table or None
        registered: list[str] = []
        removed: list[str] = []
        removed_dbs: list[str] = []
        on_disk: dict[str, dict[str, str]] = {}  # db -> {table: location}
        try:
            entries = self.file_io.list_status(self.warehouse)
        except (FileNotFoundError, OSError):
            entries = []
        for st in entries:
            base = st.path.rstrip("/").rsplit("/", 1)[-1]
            if not base.endswith(".db"):
                continue
            db = base[: -len(".db")]
            if scope_db and db != scope_db:
                continue
            tables: dict[str, str] = {}
            for ts in self.file_io.list_status(st.path):
                tname = ts.path.rstrip("/").rsplit("/", 1)[-1]
                if scope_table and tname != scope_table:
                    continue
                if SchemaManager(self.file_io, ts.path).latest() is not None:
                    tables[tname] = ts.path.rstrip("/")
            on_disk[db] = tables
        with self._conn() as c:
            live_locations: set[str] = set()
            for db, tname, location in list(
                c.execute("SELECT database_name, table_name, location FROM paimon_tables")
            ):
                if scope_db and (db != scope_db or (scope_table and tname != scope_table)):
                    continue
                if SchemaManager(self.file_io, location).latest() is None:
                    c.execute(
                        "DELETE FROM paimon_tables WHERE database_name = ? AND table_name = ?",
                        (db, tname),
                    )
                    removed.append(f"{db}.{tname}")
                else:
                    live_locations.add(location.rstrip("/"))
            for db, tables in on_disk.items():
                c.execute("INSERT OR IGNORE INTO paimon_databases (name) VALUES (?)", (db,))
                for tname, location in tables.items():
                    if location in live_locations:
                        continue  # already registered (possibly under another name)
                    cur = c.execute(
                        "INSERT OR IGNORE INTO paimon_tables (database_name, table_name, location) "
                        "VALUES (?, ?, ?)",
                        (db, tname, location),
                    )
                    if cur.rowcount:
                        registered.append(f"{db}.{tname}")
            for (db,) in list(c.execute("SELECT name FROM paimon_databases")):
                if db in on_disk or scope_table or (scope_db and db != scope_db):
                    continue  # scoped repair never drops other databases
                has_rows = c.execute(
                    "SELECT 1 FROM paimon_tables WHERE database_name = ? LIMIT 1", (db,)
                ).fetchone()
                if not has_rows:
                    c.execute("DELETE FROM paimon_databases WHERE name = ?", (db,))
                    removed_dbs.append(db)
        return {
            "registered": sorted(registered),
            "removed": sorted(removed),
            "removed_databases": sorted(removed_dbs),
        }

    def lock(self, identifier: "Identifier | str") -> "JdbcCatalogLock":
        ident = Identifier.parse(identifier) if isinstance(identifier, str) else identifier
        return JdbcCatalogLock(self.db_path, f"{ident.database}.{ident.table}")


class JdbcCatalogLock(CatalogLock):
    """The lock dialect (reference JdbcDistributedLockDialect): acquire =
    INSERT of a unique lock row (the database serializes racers), stale rows
    time out, release = DELETE of OUR row only."""

    def __init__(self, db_path: str, lock_id: str, timeout: float = 60.0, stale_ttl: float = 300.0):
        self.db_path = db_path
        self.lock_id = lock_id
        self.timeout = timeout
        self.stale_ttl = stale_ttl
        self.holder = uuid.uuid4().hex
        # standalone use (commit.catalog-lock.type=jdbc without a JdbcCatalog):
        # the lock table must exist before the first acquire
        with self._conn() as c:
            c.executescript(_LOCK_TABLE_DDL)

    @contextmanager
    def _conn(self):
        # one short-lived connection per operation; closed (not just
        # committed) so per-op/per-heartbeat connections cannot leak fds
        c = sqlite3.connect(self.db_path, timeout=30.0)
        c.execute("PRAGMA busy_timeout = 30000")
        try:
            with c:
                yield c
        finally:
            c.close()

    @contextmanager
    def lock(self, database: str = "", table: str = ""):
        deadline = time.monotonic() + self.timeout
        while True:
            with self._conn() as c:
                c.execute(
                    "DELETE FROM paimon_distributed_locks WHERE lock_id = ? AND acquired_at < ?",
                    (self.lock_id, time.time() - self.stale_ttl),
                )
                try:
                    c.execute(
                        "INSERT INTO paimon_distributed_locks (lock_id, holder, acquired_at) "
                        "VALUES (?, ?, ?)",
                        (self.lock_id, self.holder, time.time()),
                    )
                    break
                except sqlite3.IntegrityError:
                    pass
            if time.monotonic() > deadline:
                raise TimeoutError(f"could not acquire jdbc lock {self.lock_id}")
            time.sleep(0.05)
        # heartbeat: refresh acquired_at so a long commit is never mistaken
        # for a crashed holder and swept by a waiter (same protection as
        # FileBasedCatalogLock)
        stop = threading.Event()

        def beat():
            interval = self.stale_ttl / 3
            while not stop.wait(interval):
                try:
                    with self._conn() as c:
                        cur = c.execute(
                            "UPDATE paimon_distributed_locks SET acquired_at = ? "
                            "WHERE lock_id = ? AND holder = ?",
                            (time.time(), self.lock_id, self.holder),
                        )
                        if cur.rowcount == 0:
                            return  # row swept/stolen: lock confirmed lost
                    interval = self.stale_ttl / 3
                except Exception:
                    # transient sqlite busy/IO hiccup: keep beating (retry
                    # sooner) instead of abandoning the heartbeat while the
                    # holder is still in the critical section.
                    interval = min(1.0, self.stale_ttl / 10)

        hb = threading.Thread(target=beat, daemon=True)
        hb.start()
        try:
            yield
        finally:
            stop.set()
            hb.join(timeout=1.0)
            with self._conn() as c:
                c.execute(
                    "DELETE FROM paimon_distributed_locks WHERE lock_id = ? AND holder = ?",
                    (self.lock_id, self.holder),
                )
