"""Space-filling curves for clustering sort.

Parity: /root/reference/paimon-core/.../sort/zorder/ZIndexer.java:63 and
paimon-common/.../sort/hilbert/HilbertIndexer.java:63 — multi-column cluster
keys for sort-compaction, so range predicates on any indexed column prune
well. Inputs are the order-preserving uint32 lanes from data.keys; outputs
are uint32 lane matrices whose lexicographic order IS the curve order, ready
for the same device sort kernel.

Both transforms are vectorized bit manipulation over whole columns (numpy);
32*K scalar-bit steps of vector ops, no per-row loops.
"""

from __future__ import annotations

import numpy as np

__all__ = ["z_order_lanes", "hilbert_lanes"]


def z_order_lanes(lanes: np.ndarray) -> np.ndarray:
    """(n, K) uint32 -> (n, K) uint32 whose lex order equals Z-curve order
    (bit-interleave: msb of col0, msb of col1, ..., next bit of col0, ...)."""
    n, k = lanes.shape
    if k <= 1:
        return lanes.copy()
    out = np.zeros((n, k), dtype=np.uint32)
    for b in range(31, -1, -1):  # source bit, msb first
        for c in range(k):
            bit = (lanes[:, c] >> np.uint32(b)) & np.uint32(1)
            p = (31 - b) * k + c  # global position from the msb
            out_lane = p // 32
            out_bit = 31 - (p % 32)
            out[:, out_lane] |= bit << np.uint32(out_bit)
    return out


def hilbert_lanes(lanes: np.ndarray, bits: int = 32) -> np.ndarray:
    """(n, K) uint32 -> (n, K) uint32 in Hilbert-curve order (Skilling's
    transform, vectorized across rows)."""
    n, k = lanes.shape
    if k <= 1:
        return lanes.copy()
    x = lanes.astype(np.uint32).T.copy()  # (K, n)
    m = np.uint32(1) << np.uint32(bits - 1)
    # inverse undo excess work (Skilling 2004, transposed form)
    q = m
    while q > 1:
        p = np.uint32(q - 1)
        for i in range(k):
            swap = (x[i] & q) != 0
            # invert or exchange low bits
            x[0] = np.where(swap, x[0] ^ p, x[0])
            t = (x[0] ^ x[i]) & p
            t = np.where(swap, np.uint32(0), t)
            x[0] ^= t
            x[i] ^= t
        q >>= np.uint32(1)
    # gray encode
    for i in range(1, k):
        x[i] ^= x[i - 1]
    t = np.zeros(n, dtype=np.uint32)
    q = m
    while q > 1:
        t = np.where((x[k - 1] & q) != 0, t ^ np.uint32(q - 1), t)
        q >>= np.uint32(1)
    for i in range(k):
        x[i] ^= t
    # x now holds the transposed hilbert index: bit-interleave to compare
    return z_order_lanes(x.T)
