"""Device-side skew-aware equi-joins: hash + sort-merge over uint32 key lanes.

The JSPIM move (PAPERS.md) for this codebase: JOIN becomes the same shape as
every merge — normalized uint32 key lanes, one stable device sort, segment
reductions — instead of a host hash table probed row at a time. The pieces
deliberately reuse the merge machinery so joins inherit every optimization
that landed for merges:

  * key encoding rides `data/keys.py` — typed columns become order- and
    equality-preserving uint32 lanes; string/bytes keys rank against one
    pool built over BOTH sides (exact, collision-free);
  * lane compression rides `ops/lanes.py` — one GLOBAL `LanePlan` over both
    sides (the ISSUE 7 rule: per-side plans would pack incomparably)
    truncates and packs the lanes, so a composite key often joins as a
    single fused uint32 operand;
  * the sort-merge kernel rides the `ops/merge.sorted_segments` seam — the
    build and probe rows concatenate with a side lane as the leading
    sequence lane, one stable sort groups equal keys into segments with
    build rows first, and `sort-engine=pallas` is inherited for free;
  * the code domain rides `ops/dicts.py` — when both sides of a key column
    are dictionary-backed, their pools unify once (O(|pool|) object work)
    and the join matches remapped uint32 codes with ZERO string
    materialization end to end (`join{code_domain_joins}`), falling back
    per join past `merge.dict-domain.pool-limit`.

Skew (the JSPIM headline): one hot probe key must not serialize a
partition. When the probe side is large enough to split (`join.chunk-rows`,
or an explicit `join.partitions`), a key-histogram pass over the probe
lanes finds heavy hitters (probe share >= `join.skew-factor` x the fair
per-partition share); light keys hash-partition both sides as usual, heavy
keys SPLIT their probe rows round-robin across every partition and
replicate their (few) build rows to each — each probe row still meets each
matching build row exactly once, and no partition is left holding the hot
key alone (`join{skew_keys, skew_split_rows}`).

Two tiers:

  * `join_batches` — the full two-batch join (SQL `JOIN`, benchmarks):
    per-query encoding, global lane plan, skew partitioning, device or
    numpy kernels; output pairs ordered by (probe row, build row).
  * `JoinIndex` — a cached build-side structure for repeated probes
    (lookup tables): build lanes encode once per refresh, fold to <= 64-bit
    codes, and each probe batch pays one searchsorted — the vectorized
    replacement for the per-row `FullCacheLookupTable.get` loop. Probe
    values absent from the build pools are masked exactly (never a false
    match), so probe-side misses need no shared pool.

Both tiers produce BIT-IDENTICAL output to the host oracle (numpy/pandas)
across seeds, skew, null rates, dict/non-dict and lane-compression on/off —
tests/test_join.py pins exactly that. NULL join keys never match (SQL
semantics): inner drops them, left emits the row unmatched.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field
from typing import Mapping, Sequence

import numpy as np

from ..types import TypeRoot

__all__ = [
    "JoinError",
    "JoinResult",
    "JoinIndex",
    "join_batches",
    "materialize_join",
    "resolve_join_engine",
    "partition_executor",
]

_STRING_ROOTS = (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)

# distributed-partition seam (ISSUE 15, declared PR 12 follow-up): when an
# executor is installed, the skew-planned per-partition kernels run through
# it — the cluster client routes partition i to the worker owning bucket
# (i % num_buckets), so the JSPIM split spans worker processes. The executor
# receives [(probe_lanes, build_lanes, algorithm, engine), ...] and returns
# the per-partition (left_take, right_take) index pairs, which compose into
# a JoinResult bit-identical to the local loop (partition order preserved).
import contextlib
import contextvars

_PART_EXECUTOR: "contextvars.ContextVar" = contextvars.ContextVar(
    "paimon_tpu_join_part_executor", default=None
)


@contextlib.contextmanager
def partition_executor(fn):
    """Install `fn([(ll, rl, algorithm, engine), ...]) -> [(lt, rt), ...]`
    as the join-partition executor for the calling context."""
    token = _PART_EXECUTOR.set(fn)
    try:
        yield
    finally:
        _PART_EXECUTOR.reset(token)


class JoinError(ValueError):
    pass


def _metrics():
    from ..metrics import join_metrics

    return join_metrics()


# ---------------------------------------------------------------------------
# option / engine resolution
# ---------------------------------------------------------------------------

def _opt(options, key: str, default):
    """join.* options arrive as a table Options object, a plain str->str
    mapping (SQL hints), or None."""
    if options is None:
        return default
    get = getattr(options, "to_map", None)
    data = get() if get is not None else options
    v = data.get(key)
    if v is None:
        return default
    if isinstance(default, bool):
        return str(v).strip().lower() in ("1", "on", "true")
    if isinstance(default, int):
        return int(v)
    if isinstance(default, float):
        return float(v)
    return str(v)


def resolve_join_engine(options=None, rows: int = 0) -> str:
    """'numpy' | 'xla' | 'pallas'. Resolution mirrors the merge kernels
    (core/mergefn.effective_sort_engine): the PAIMON_TPU_JOIN_ENGINE env
    (test forcing knob) beats the `join.engine` option beats auto. Auto
    keeps small joins on the host lexsort (dispatch overhead dominates) and
    CPU-only platforms host-side unless PAIMON_TPU_FORCE_DEVICE_ENGINE
    pins the device path; the device flavor follows the table's sort-engine
    choice so `sort-engine=pallas` carries into the join sort."""
    env = os.environ.get("PAIMON_TPU_JOIN_ENGINE", "").strip().lower()
    choice = env or _opt(options, "join.engine", "auto")
    if choice in ("xla", "xla-segmented"):
        return "xla"
    if choice in ("numpy", "pallas"):
        return choice
    # auto
    if rows < _opt(options, "join.device-rows", 4096):
        return "numpy"
    from .merge import resolved_platform_is_cpu

    if resolved_platform_is_cpu() and os.environ.get("PAIMON_TPU_FORCE_DEVICE_ENGINE", "") != "1":
        return "numpy"
    return _device_flavor(options)


def _device_flavor(options) -> str:
    sort_env = os.environ.get("PAIMON_TPU_SORT_ENGINE", "").strip().lower()
    choice = _opt(options, "sort-engine", "") or sort_env
    return "pallas" if choice == "pallas" else "xla"


# ---------------------------------------------------------------------------
# key encoding: typed columns (both sides) -> comparable uint32 lanes
# ---------------------------------------------------------------------------

@dataclass
class _EncodedKeys:
    left: np.ndarray  # (n_l, L) uint32
    right: np.ndarray  # (n_r, L) uint32
    left_live: np.ndarray  # bool — non-null key, eligible to match
    right_live: np.ndarray
    code_domain_cols: int = 0  # key columns matched in the code domain


def _null_filled_values(col, pool):
    """Object values with nulls replaced by a harmless present value (the
    validity mask already bars those rows from matching; the substitute
    only keeps the pool ranking total)."""
    values = col.values
    if col.validity is None:
        return values
    values = values.copy()
    values[~col.validity] = pool[0] if len(pool) else ""
    return values


def _present_string_pool(cols) -> np.ndarray:
    """Sorted distinct PRESENT values across the given string columns —
    exact_string_pool, except NULL slots (join keys may be nullable, unlike
    merge keys) are dropped before the pool builds."""
    from ..data.keys import build_string_pool, exact_string_pool
    from .dicts import cache_usable

    cols = list(cols)
    if cols and all(cache_usable(c) for c in cols):
        return exact_string_pool(cols)  # prunes through validity already
    parts = []
    for c in cols:
        v = c.values
        if c.validity is not None:
            v = v[c.validity]
        parts.append(v)
    return build_string_pool(parts)


def _try_code_domain(lc, rc, limit) -> tuple[np.ndarray, np.ndarray] | None:
    """One key column pair in the code domain: both sides dictionary-backed
    -> unify the two pools and remap both code vectors (ops.dicts). Returns
    (left_lane, right_lane) uint32 or None (expanded fallback)."""
    from .dicts import cache_usable, remap_codes, resolve_pool_limit, unify_pools

    if not (cache_usable(lc) and cache_usable(rc)):
        return None
    lp, lcodes = lc.dict_cache
    rp, rcodes = rc.dict_cache
    if len(lp) + len(rp) > resolve_pool_limit(limit):
        return None
    unified, (lmap, rmap) = unify_pools([lp, rp])
    if len(unified) > resolve_pool_limit(limit):
        return None
    return remap_codes(lmap, lcodes), remap_codes(rmap, rcodes)


def _encode_join_keys(left, right, left_keys, right_keys, pool_limit=None) -> _EncodedKeys:
    """Shared-space lanes for the key columns of both sides. Equality of the
    lane tuples == typed equality of the key tuples (the data/keys.py
    contract), with string ranks taken against ONE pool covering both
    sides — or, when both sides are dictionary-backed, against the unified
    code domain with zero string materialization."""
    from ..data.keys import _encode_column

    if len(left_keys) != len(right_keys) or not left_keys:
        raise JoinError(f"key arity mismatch: {list(left_keys)} vs {list(right_keys)}")
    n_l, n_r = left.num_rows, right.num_rows
    left_live = np.ones(n_l, dtype=np.bool_)
    right_live = np.ones(n_r, dtype=np.bool_)
    lanes_l: list[np.ndarray] = []
    lanes_r: list[np.ndarray] = []
    code_cols = 0
    for lname, rname in zip(left_keys, right_keys):
        lf, rf = left.schema.field(lname), right.schema.field(rname)
        if lf.type.root != rf.type.root:
            raise JoinError(
                f"join key type mismatch: {lname} is {lf.type.root}, {rname} is {rf.type.root}"
            )
        lc, rc = left.column(lname), right.column(rname)
        if lc.validity is not None:
            left_live &= lc.validity
        if rc.validity is not None:
            right_live &= rc.validity
        coded = _try_code_domain(lc, rc, pool_limit)
        if coded is not None:
            lanes_l.append(coded[0].astype(np.uint32, copy=False))
            lanes_r.append(coded[1].astype(np.uint32, copy=False))
            code_cols += 1
            continue
        root = lf.type.root
        if root in _STRING_ROOTS:
            pool = _present_string_pool([lc, rc])
            if len(pool) == 0:  # every key NULL on both sides: no row matches
                lanes_l.append(np.zeros(n_l, dtype=np.uint32))
                lanes_r.append(np.zeros(n_r, dtype=np.uint32))
                left_live &= False
                right_live &= False
                continue
            lanes_l.extend(_encode_column(_null_filled_values(lc, pool), root, pool))
            lanes_r.extend(_encode_column(_null_filled_values(rc, pool), root, pool))
        else:
            lanes_l.extend(_encode_column(lc.values, root, None))
            lanes_r.extend(_encode_column(rc.values, root, None))
    stack = lambda ls, n: (  # noqa: E731 — tiny local
        np.stack(ls, axis=1).astype(np.uint32, copy=False)
        if ls
        else np.zeros((n, 0), dtype=np.uint32)
    )
    return _EncodedKeys(stack(lanes_l, n_l), stack(lanes_r, n_r), left_live, right_live, code_cols)


# ---------------------------------------------------------------------------
# kernels: hash probe (single lane) and sort-merge (multi lane)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _hash_probe_fn():
    """Jitted single-lane probe: stable-sort the build lane (pads, filled
    with the u32 max sentinel, sort last), binary-search every probe value,
    clip the hit range to the valid build prefix so a real key equal to the
    sentinel can never count pad rows. Downloads O(n) int32 — the expansion
    to pairs is host numpy."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(build_pad, build_lane, probe_lane, nr):
        m = build_lane.shape[0]
        iota = jnp.arange(m, dtype=jnp.int32)
        _, sl, order = jax.lax.sort([build_pad, build_lane, iota], num_keys=2, is_stable=True)
        lo = jnp.searchsorted(sl, probe_lane, side="left").astype(jnp.int32)
        hi = jnp.searchsorted(sl, probe_lane, side="right").astype(jnp.int32)
        lo = jnp.minimum(lo, nr)
        hi = jnp.minimum(hi, nr)
        return order, lo, hi - lo

    return f


def _hash_pairs(ll: np.ndarray, rl: np.ndarray, engine: str):
    """Single-lane equi-join core: (probe_counts, probe_starts, mapping)
    where mapping[sorted_pos] = build row and each probe row's matches are
    mapping[starts : starts+counts] (build rows ascending)."""
    n_l, n_r = ll.shape[0], rl.shape[0]
    lane_l, lane_r = ll[:, 0], rl[:, 0]
    if engine == "numpy" or n_r == 0 or n_l == 0:
        order = np.argsort(lane_r, kind="stable").astype(np.int64)
        dom = int(max(lane_r.max() if n_r else 0, lane_l.max() if n_l else 0)) + 1
        if 0 < dom <= max(1 << 20, 4 * (n_l + n_r)):
            # dense domain (dictionary codes, min-shifted lanes): direct
            # addressing — two O(n) gathers instead of two 1M-row binary
            # searches. bincount + exclusive cumsum IS the hash table.
            counts_k = np.bincount(lane_r, minlength=dom)
            starts_k = np.concatenate([[0], np.cumsum(counts_k)[:-1]])
            return (
                counts_k[lane_l].astype(np.int64),
                starts_k[lane_l].astype(np.int64),
                order,
            )
        srt = lane_r[order]
        lo = np.searchsorted(srt, lane_l, side="left")
        hi = np.searchsorted(srt, lane_l, side="right")
        return (hi - lo).astype(np.int64), lo.astype(np.int64), order
    from .merge import pad_size

    m_r, m_l = pad_size(n_r), pad_size(n_l)
    bpad = np.zeros(m_r, dtype=np.uint8)
    bpad[n_r:] = 1
    blane = np.full(m_r, 0xFFFFFFFF, dtype=np.uint32)
    blane[:n_r] = lane_r
    plane = np.zeros(m_l, dtype=np.uint32)
    plane[:n_l] = lane_l
    order, lo, counts = _hash_probe_fn()(bpad, blane, plane, np.int32(n_r))
    return (
        np.asarray(counts)[:n_l].astype(np.int64),
        np.asarray(lo)[:n_l].astype(np.int64),
        np.asarray(order).astype(np.int64),
    )


def _sortmerge_pairs(ll: np.ndarray, rl: np.ndarray, engine: str):
    """Multi-lane equi-join core through the ONE merge preamble: concat
    [build; probe] rows, sort by (key lanes, side, input order) via
    `sorted_segments` (device) or np.lexsort (host), segment by key. Build
    rows lead each segment (side lane 0 < 1), so a probe row's matches are
    the first right_count slots of its segment. Returns (counts, starts,
    mapping) in the same contract as _hash_pairs — mapping is the sorted
    permutation, whose build slots hold build row indices directly."""
    n_r, n_l = rl.shape[0], ll.shape[0]
    n = n_r + n_l
    k = ll.shape[1]
    joint = np.vstack([rl, ll])
    side = np.zeros(n, dtype=np.uint32)
    side[n_r:] = 1
    if engine == "numpy" or n == 0:
        keys = [side] + [joint[:, i] for i in range(k - 1, -1, -1)]
        perm = np.lexsort(keys).astype(np.int64)
        srt = joint[perm]
        neq = (srt[1:] != srt[:-1]).any(axis=1) if n > 1 else np.zeros(0, dtype=bool)
        seg = np.concatenate([[0], np.cumsum(neq)]).astype(np.int64) if n else np.zeros(0, np.int64)
    else:
        from .merge import _merge_plan_padded

        plan = _merge_plan_padded(joint, side[:, None], None, engine if engine == "pallas" else "xla")
        perm = plan.perm[:n].astype(np.int64)
        seg = plan.seg_id[:n].astype(np.int64)
    is_left = perm >= n_r
    num_segs = int(seg[-1]) + 1 if n else 0
    seg_start = np.searchsorted(seg, np.arange(num_segs))
    right_count = np.bincount(seg[~is_left], minlength=num_segs) if n else np.zeros(0, np.int64)
    left_slots = np.flatnonzero(is_left)
    left_inputs = perm[left_slots] - n_r
    lsegs = seg[left_slots]
    counts = np.zeros(n_l, dtype=np.int64)
    starts = np.zeros(n_l, dtype=np.int64)
    counts[left_inputs] = right_count[lsegs]
    starts[left_inputs] = seg_start[lsegs]
    return counts, starts, perm


def _expand_pairs(counts: np.ndarray, starts: np.ndarray, mapping: np.ndarray):
    """(per-probe counts, per-probe start into mapping) -> flat (left, right)
    index pairs, probe-major, build rows ascending within each probe row."""
    n_l = counts.shape[0]
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    if total <= n_l and counts.max() <= 1:
        # unique build keys (the PK-dimension case): no fan-out, the pair
        # list is just the matched probe rows — skip the repeat machinery
        lt = np.flatnonzero(counts).astype(np.int64)
        return lt, mapping[starts[lt]]
    lt = np.repeat(np.arange(n_l, dtype=np.int64), counts)
    cumex = np.concatenate([[0], np.cumsum(counts)[:-1]])
    offs = np.arange(total, dtype=np.int64) - np.repeat(cumex, counts) + np.repeat(starts, counts)
    return lt, mapping[offs]


def _join_part(ll: np.ndarray, rl: np.ndarray, algorithm: str, engine: str):
    """Inner-join one partition of live rows; returns (lt, rt) local pairs."""
    n_l, n_r = ll.shape[0], rl.shape[0]
    if n_l == 0 or n_r == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    if ll.shape[1] == 0:
        # zero-width key (batch-constant on both sides): every live probe row
        # matches every live build row — the degenerate cross product
        lt = np.repeat(np.arange(n_l, dtype=np.int64), n_r)
        rt = np.tile(np.arange(n_r, dtype=np.int64), n_l)
        return lt, rt
    if algorithm == "hash" and ll.shape[1] == 1:
        counts, starts, mapping = _hash_pairs(ll, rl, engine)
    else:
        counts, starts, mapping = _sortmerge_pairs(ll, rl, engine)
    return _expand_pairs(counts, starts, mapping)


# ---------------------------------------------------------------------------
# skew-aware partitioning (JSPIM)
# ---------------------------------------------------------------------------

def _key_ids(left_lanes: np.ndarray, right_lanes: np.ndarray):
    """Joint dense key ids over both sides (void-view unique: one host pass,
    no per-row python). Returns (left_ids, right_ids, num_keys)."""
    k = left_lanes.shape[1]
    joint = np.ascontiguousarray(np.vstack([left_lanes, right_lanes]))
    if k == 0:
        return (
            np.zeros(left_lanes.shape[0], dtype=np.int64),
            np.zeros(right_lanes.shape[0], dtype=np.int64),
            1,
        )
    if k == 1:  # single fused operand: a plain u32 sort, no void-row compares
        _, inv = np.unique(joint[:, 0], return_inverse=True)
    else:
        view = joint.view([("", np.uint32)] * k).ravel()
        _, inv = np.unique(view, return_inverse=True)
    inv = inv.astype(np.int64)
    return inv[: left_lanes.shape[0]], inv[left_lanes.shape[0]:], int(inv.max()) + 1 if len(inv) else 0


@dataclass
class _SkewPlan:
    parts: list[tuple[np.ndarray, np.ndarray]]  # per partition: (probe idx, build idx)
    skew_keys: int = 0
    skew_split_rows: int = 0


def _plan_partitions(
    left_lanes, right_lanes, live_l: np.ndarray, live_r: np.ndarray,
    num_parts: int, skew_factor: float,
) -> _SkewPlan:
    """Split live probe/build rows into num_parts key-disjoint partitions,
    except for heavy hitters: a key holding >= skew_factor x the fair
    per-partition probe share gets its probe rows dealt round-robin across
    ALL partitions and its build rows replicated to each — the JSPIM skew
    split. Build rows whose key never appears live on the probe side are
    dropped (they cannot match under inner OR left semantics)."""
    li = np.flatnonzero(live_l)
    ri = np.flatnonzero(live_r)
    lid, rid, nk = _key_ids(left_lanes[li], right_lanes[ri])
    n_live = len(li)
    probe_counts = np.bincount(lid, minlength=max(nk, 1))
    # a key's probe rows cannot be subdivided by hashing, so any key holding
    # a meaningful fraction of one partition's fair share already skews that
    # partition — split it (the threshold is in units of the fair share)
    heavy_cut = max(skew_factor * n_live / max(num_parts, 1), 2.0)
    heavy = probe_counts >= heavy_cut
    if num_parts <= 1:
        heavy[:] = False
    # key -> partition for light keys (Knuth multiplicative spread)
    key_part = (np.arange(len(probe_counts), dtype=np.uint64) * np.uint64(2654435761)) % np.uint64(num_parts)
    l_heavy = heavy[lid]
    l_part = key_part[lid].astype(np.int64)
    # heavy probe rows: round-robin deal, per-row position within its key
    if l_heavy.any():
        l_part[l_heavy] = np.arange(int(l_heavy.sum()), dtype=np.int64) % num_parts
    r_matched = probe_counts[rid] > 0 if len(rid) else np.zeros(0, dtype=bool)
    r_heavy = heavy[rid] & r_matched if len(rid) else np.zeros(0, dtype=bool)
    r_part = key_part[rid].astype(np.int64) if len(rid) else np.zeros(0, np.int64)
    parts: list[tuple[np.ndarray, np.ndarray]] = []
    heavy_build = ri[r_heavy]
    for p in range(num_parts):
        probe_p = li[l_part == p]
        build_p = ri[r_matched & ~r_heavy & (r_part == p)]
        if len(heavy_build):
            build_p = np.sort(np.concatenate([build_p, heavy_build]))
        parts.append((probe_p, build_p))
    return _SkewPlan(
        parts,
        skew_keys=int(heavy.sum()),
        skew_split_rows=int(l_heavy.sum()),
    )


# ---------------------------------------------------------------------------
# the full two-batch join
# ---------------------------------------------------------------------------

@dataclass
class JoinResult:
    """Flat matched pairs, probe-major: left_take ascending (stable), build
    rows ascending within each probe row. right_take is -1 where a LEFT
    join kept an unmatched probe row."""

    left_take: np.ndarray
    right_take: np.ndarray
    n_left: int
    n_right: int
    how: str = "inner"
    stats: dict = field(default_factory=dict)

    @property
    def matched(self) -> np.ndarray:
        return self.right_take >= 0

    @property
    def num_rows(self) -> int:
        return len(self.left_take)


def join_batches(
    left,
    right,
    left_keys: Sequence[str],
    right_keys: Sequence[str],
    how: str = "inner",
    options: "Mapping | None" = None,
    engine: str | None = None,
) -> JoinResult:
    """Equi-join two ColumnBatches on aligned key column lists.

    how='inner' keeps matched pairs; how='left' additionally emits every
    unmatched probe row once with right_take == -1. NULL keys never match.
    Output order is deterministic: probe rows in input order, each probe
    row's matches in build input order — the same order a host nested loop
    (and the pandas oracle in the parity suite) produces."""
    import time as _time

    if how not in ("inner", "left"):
        raise JoinError(f"unsupported join type {how!r} (inner | left)")
    g = _metrics()
    t0 = _time.perf_counter()
    enc = _encode_join_keys(
        left, right, list(left_keys), list(right_keys),
        pool_limit=_opt(options, "merge.dict-domain.pool-limit", None) if options else None,
    )
    n_l, n_r = left.num_rows, right.num_rows
    engine = engine or resolve_join_engine(options, rows=n_l + n_r)
    from .lanes import plan_lanes_global, apply_plan, resolve_compress

    comp_opt = _opt(options, "merge.lane-compression", True) if options is not None else None
    if resolve_compress(comp_opt):
        plan = plan_lanes_global([enc.left, enc.right])
        ll = apply_plan(plan, enc.left)
        rl = apply_plan(plan, enc.right)
    else:
        ll, rl = enc.left, enc.right
    algorithm = _opt(options, "join.algorithm", "auto")
    if algorithm == "auto":
        algorithm = "hash" if ll.shape[1] == 1 else "sort-merge"
    elif algorithm == "hash" and ll.shape[1] != 1:
        algorithm = "sort-merge"  # hash needs a single fused operand
    chunk_rows = _opt(options, "join.chunk-rows", 1 << 20)
    num_parts = _opt(options, "join.partitions", 0)
    if num_parts <= 0:
        num_parts = max(1, -(-n_l // max(chunk_rows, 1)))
    skew_factor = _opt(options, "join.skew-factor", 0.5)
    t_build = _time.perf_counter()

    if num_parts > 1:
        plan_p = _plan_partitions(ll, rl, enc.left_live, enc.right_live, num_parts, skew_factor)
        lt_all, rt_all = [], []
        part_exec = _PART_EXECUTOR.get()
        if part_exec is not None:
            pairs = part_exec(
                [(ll[pi], rl[bi], algorithm, engine) for pi, bi in plan_p.parts]
            )
            for (probe_idx, build_idx), (lt, rt) in zip(plan_p.parts, pairs):
                lt_all.append(probe_idx[lt])
                rt_all.append(build_idx[rt])
        else:
            for probe_idx, build_idx in plan_p.parts:
                lt, rt = _join_part(ll[probe_idx], rl[build_idx], algorithm, engine)
                lt_all.append(probe_idx[lt])
                rt_all.append(build_idx[rt])
        lt_g = np.concatenate(lt_all) if lt_all else np.empty(0, np.int64)
        rt_g = np.concatenate(rt_all) if rt_all else np.empty(0, np.int64)
        skew_keys, skew_rows = plan_p.skew_keys, plan_p.skew_split_rows
    else:
        li = np.flatnonzero(enc.left_live)
        ri = np.flatnonzero(enc.right_live)
        if len(li) == n_l and len(ri) == n_r:
            lt_g, rt_g = _join_part(ll, rl, algorithm, engine)
        else:
            lt, rt = _join_part(ll[li], rl[ri], algorithm, engine)
            lt_g, rt_g = li[lt], ri[rt]
        skew_keys = skew_rows = 0

    sorted_already = num_parts == 1  # _expand_pairs emits probe-major order
    if how == "left":
        matched = np.zeros(n_l, dtype=bool)
        matched[lt_g] = True
        miss = np.flatnonzero(~matched)
        if len(miss):
            lt_g = np.concatenate([lt_g, miss])
            rt_g = np.concatenate([rt_g, np.full(len(miss), -1, dtype=np.int64)])
            sorted_already = False
    if not sorted_already:
        order = np.argsort(lt_g, kind="stable")
        lt_g, rt_g = lt_g[order], rt_g[order]
    res = JoinResult(
        left_take=lt_g,
        right_take=rt_g,
        n_left=n_l,
        n_right=n_r,
        how=how,
        stats={
            "algorithm": algorithm,
            "engine": engine,
            "partitions": num_parts,
            "skew_keys": skew_keys,
            "skew_split_rows": skew_rows,
            "code_domain_cols": enc.code_domain_cols,
            "lanes": ll.shape[1],
        },
    )
    g.counter("joins").inc()
    g.counter("rows_probed").inc(n_l)
    g.counter("rows_matched").inc(int(res.matched.sum()))
    g.counter("hash_joins" if algorithm == "hash" else "sort_merge_joins").inc()
    if enc.code_domain_cols:
        g.counter("code_domain_joins").inc()
    if skew_keys:
        g.counter("skew_keys").inc(skew_keys)
        g.counter("skew_split_rows").inc(skew_rows)
    g.histogram("build_ms").update((t_build - t0) * 1000)
    g.histogram("probe_ms").update((_time.perf_counter() - t_build) * 1000)
    return res


# ---------------------------------------------------------------------------
# materialization
# ---------------------------------------------------------------------------

def _take_nullable(col, take: np.ndarray, matched: np.ndarray):
    """col.take(take) with rows where matched is False forced NULL (the
    unmatched half of a LEFT join). Stays in whatever domain the column is
    in — code-backed columns gather codes, never strings."""
    from ..data.batch import Column

    if matched.all():
        return col.take(take)
    safe = np.where(matched, take, 0)
    out = col.take(safe)
    validity = out.valid_mask() & matched
    if out.is_code_backed:
        pool, codes = out.dict_cache
        return Column.from_codes(pool, codes, validity)
    if out._values is None:
        res = Column(validity=validity, arrow=out.arrow)
    else:
        res = Column(out._values, validity)
    res.dict_cache = out.dict_cache
    return res


def materialize_join(
    left,
    right,
    res: JoinResult,
    left_cols: Sequence[tuple[str, str]],
    right_cols: Sequence[tuple[str, str]],
):
    """Gather the joined output batch: left_cols / right_cols are
    (source column, output name) pairs. Right-side columns of a LEFT join
    carry NULL at unmatched rows. All gathers are structural Column ops —
    code-backed and arrow-backed columns never materialize objects here."""
    from ..data.batch import ColumnBatch
    from ..types import DataField, RowType

    matched = res.matched
    fields = []
    cols = {}
    for src, out in left_cols:
        fields.append((out, left.schema.field(src).type))
        cols[out] = left.column(src).take(res.left_take)
    for src, out in right_cols:
        fields.append((out, right.schema.field(src).type))
        cols[out] = _take_nullable(right.column(src), res.right_take, matched)
    schema = RowType(tuple(DataField(i, n, t) for i, (n, t) in enumerate(fields)))
    return ColumnBatch(schema, cols)


# ---------------------------------------------------------------------------
# JoinIndex: cached build side for repeated probes (lookup joins)
# ---------------------------------------------------------------------------

class JoinIndex:
    """Build once per refresh epoch, probe many times. The build side's key
    lanes encode against build-only pools, truncate/pack through the lane
    planner (no OVC — equality only), fold into <= 64-bit codes, and sort
    once. Each probe batch pays: per-key-column encode against the cached
    pool with an exact `present` mask (a probe value outside the build's
    pool or lane range is provably unmatched — masked, never a false
    match), one searchsorted, one host expansion. Keys too wide to fold
    (> 2 packed operands) keep the raw batch and delegate to join_batches
    per probe call."""

    def __init__(self, batch, key_names: Sequence[str]):
        from .lanes import lane_stats, plan_lanes_from_stats, apply_plan

        self.batch = batch
        self.key_names = list(key_names)
        self.pools: dict[str, np.ndarray] = {}
        n = batch.num_rows
        live = np.ones(n, dtype=np.bool_)
        lanes: list[np.ndarray] = []
        self._col_lanes: list[tuple[str, TypeRoot, int]] = []  # (name, root, lane count)
        from ..data.keys import _encode_column

        for name in self.key_names:
            col = batch.column(name)
            root = batch.schema.field(name).type.root
            if col.validity is not None:
                live &= col.validity
            if root in _STRING_ROOTS:
                pool = _present_string_pool([col])
                self.pools[name] = pool
                if len(pool) == 0:  # all-null build column: nothing matches
                    live &= False
                    got = [np.zeros(n, dtype=np.uint32)]
                elif _cache_full(col):
                    got = [self._ranks_cached(pool, col)]
                else:
                    got = _encode_column(_null_filled_values(col, pool), root, pool)
            else:
                got = _encode_column(col.values, root, None)
            lanes.extend(got)
            self._col_lanes.append((name, root, len(got)))
        self.lanes = (
            np.stack(lanes, axis=1).astype(np.uint32, copy=False)
            if lanes
            else np.zeros((n, 0), dtype=np.uint32)
        )
        self.live = live
        if live.any():
            self.los, self.his = lane_stats(self.lanes[live] if not live.all() else self.lanes)
        else:  # empty/all-null build: a degenerate plan no probe can match
            k = self.lanes.shape[1]
            self.los = np.zeros(k, dtype=np.uint32)
            self.his = np.zeros(k, dtype=np.uint32)
        self.plan = plan_lanes_from_stats(self.lanes.shape[1], self.los, self.his)
        packed = apply_plan(self.plan, self.lanes)
        self.wide = packed.shape[1] > 2
        if self.wide:
            return
        codes = _fold_codes(packed)
        vi = np.flatnonzero(live)
        order = np.argsort(codes[vi], kind="stable")
        self.row_of = vi[order].astype(np.int64)
        self.sorted_codes = codes[vi][order]

    @staticmethod
    def _ranks_cached(pool, col):
        from ..data.keys import _ranks_from_cache

        return _ranks_from_cache(pool, col.dict_cache)

    # ---- probe ----------------------------------------------------------
    def _probe_lanes(self, batch, keys: Sequence[str]):
        """(lanes, present): probe lanes in the build's lane space, with
        rows that provably cannot match (null key, string absent from the
        build pool, probe code pool entry absent) masked out."""
        from ..data.keys import _encode_column
        from .dicts import cache_usable, remap_codes

        n = batch.num_rows
        present = np.ones(n, dtype=np.bool_)
        lanes: list[np.ndarray] = []
        for (bname, root, cnt), pname in zip(self._col_lanes, keys):
            col = batch.column(pname)
            proot = batch.schema.field(pname).type.root
            if proot != root:
                raise JoinError(f"probe key {pname} is {proot}, index key {bname} is {root}")
            if col.validity is not None:
                present &= col.validity
            if root in _STRING_ROOTS:
                pool = self.pools[bname]
                if cache_usable(col):
                    # pool-sized compare: map the probe's pool into the build
                    # pool, flag missing entries, gather through the codes
                    ppool, codes = col.dict_cache
                    if len(pool) == 0 or len(ppool) == 0:
                        present &= False
                        lanes.append(np.zeros(n, dtype=np.uint32))
                        continue
                    idx = np.searchsorted(pool, ppool)
                    clipped = np.minimum(idx, len(pool) - 1)
                    entry_ok = pool[clipped] == ppool
                    safe_codes = np.minimum(codes, len(ppool) - 1)
                    present &= entry_ok.take(safe_codes)
                    lanes.append(remap_codes(clipped.astype(np.uint32), safe_codes))
                    continue
                values = _null_filled_values(col, pool)
                if len(pool) == 0:
                    present &= False
                    lanes.append(np.zeros(n, dtype=np.uint32))
                    continue
                ranks = np.searchsorted(pool, values)
                clipped = np.minimum(ranks, len(pool) - 1)
                present &= pool[clipped] == values
                lanes.append(clipped.astype(np.uint32))
            else:
                lanes.extend(_encode_column(col.values, root, None))
        pl = (
            np.stack(lanes, axis=1).astype(np.uint32, copy=False)
            if lanes
            else np.zeros((n, 0), dtype=np.uint32)
        )
        return pl, present

    def probe(self, batch, keys: Sequence[str] | None = None, how: str = "inner") -> JoinResult:
        """Join `batch` (probe side) against the indexed build side."""
        from .lanes import apply_plan

        keys = list(keys) if keys is not None else self.key_names
        if len(keys) != len(self._col_lanes):
            raise JoinError(f"probe key arity {len(keys)} != index arity {len(self._col_lanes)}")
        g = _metrics()
        n = batch.num_rows
        if self.wide:
            res = join_batches(batch, self.batch, keys, self.key_names, how=how)
            g.counter("index_probes").inc()
            return res
        pl, present = self._probe_lanes(batch, keys)
        # lanes the build plan dropped as constant still constrain equality;
        # kept lanes must fall inside the build's observed range or the
        # min-shift/pack would wrap — both cases are provable non-matches
        kept = set(self.plan.keep)
        for i in range(pl.shape[1]):
            lane = pl[:, i]
            if i not in kept:
                present &= lane == self.los[i]
            else:
                present &= (lane >= self.los[i]) & (lane <= self.his[i])
        clipped = np.clip(pl, self.los[None, :], self.his[None, :]) if pl.shape[1] else pl
        codes = _fold_codes(apply_plan(self.plan, clipped))
        lo = np.searchsorted(self.sorted_codes, codes, side="left")
        hi = np.searchsorted(self.sorted_codes, codes, side="right")
        counts = np.where(present, hi - lo, 0).astype(np.int64)
        lt, rt = _expand_pairs(counts, lo.astype(np.int64), self.row_of)
        if how == "left":
            miss = np.flatnonzero(counts == 0)
            lt = np.concatenate([lt, miss])
            rt = np.concatenate([rt, np.full(len(miss), -1, dtype=np.int64)])
            order = np.argsort(lt, kind="stable")
            lt, rt = lt[order], rt[order]
        g.counter("index_probes").inc()
        g.counter("rows_probed").inc(n)
        g.counter("rows_matched").inc(int((rt >= 0).sum()))
        return JoinResult(lt, rt, n, self.batch.num_rows, how=how, stats={"algorithm": "index"})


def _cache_full(col) -> bool:
    from .dicts import cache_usable

    return cache_usable(col)


def _fold_codes(packed: np.ndarray) -> np.ndarray:
    """(n, G<=2) uint32 -> (n,) uint64 codes preserving equality (and order,
    though only equality is used). G==0 folds to all-zeros: the constant key
    matched entirely through the dropped-lane present checks."""
    n, g = packed.shape
    if g == 0:
        return np.zeros(n, dtype=np.uint64)
    if g == 1:
        return packed[:, 0].astype(np.uint64)
    return (packed[:, 0].astype(np.uint64) << np.uint64(32)) | packed[:, 1].astype(np.uint64)
