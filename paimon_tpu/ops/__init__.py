"""The TPU kernels: data-parallel replacements for the reference's hot loops.

The reference's merge-on-read is a per-row k-way heap/loser-tree loop feeding
a MergeFunction (/root/reference/paimon-core/.../mergetree/compact/
SortMergeReader.java:41, SortMergeReaderWithMinHeap.java:122-179). On TPU that
branchy loop is replaced by three data-parallel stages, all jit-compiled:

  1. SORT   — one stable multi-operand `lax.sort` over uint32 key lanes +
              sequence lanes (lexicographic via num_keys);
  2. SEGMENT— same-key group detection as a shifted-compare + cumsum;
  3. REDUCE — merge engines as segment selections/reductions
              (dedup = keep-last, first-row = keep-first, partial-update =
              per-field masked last-non-null, aggregation = segment sums/
              mins/maxes with retract signs).

Everything runs on fixed padded shapes (power-of-two buckets) so XLA compiles
once per (lane-count, size-bucket) and caches.
"""

from .aggregates import AGGREGATORS, AggregateSpec, aggregate_merge
from .lanes import LanePlan, apply_plan, compress_key_lanes, plan_lanes
from .merge import (
    MergePlan,
    deduplicate_select,
    deduplicate_take,
    first_row_take,
    merge_plan,
    pad_size,
    partial_update_takes,
)

__all__ = [
    "MergePlan",
    "merge_plan",
    "pad_size",
    "deduplicate_select",
    "deduplicate_take",
    "first_row_take",
    "partial_update_takes",
    "aggregate_merge",
    "AggregateSpec",
    "AGGREGATORS",
    "LanePlan",
    "plan_lanes",
    "apply_plan",
    "compress_key_lanes",
]
