"""Field aggregators for the aggregation merge engine, as segment reductions.

Capability parity with the reference aggregator family
(/root/reference/paimon-core/.../mergetree/compact/aggregate/ — 18
FieldAggregator subclasses: sum, product, count, max, min, bool_and, bool_or,
first_value, first_non_null_value, last_value, last_non_null_value, listagg,
collect, merge_map, nested_update, primary-key, ignore-retract wrapper).

Numeric/bool/min/max/count/sum run on device as jax segment reductions over
the MergePlan's sorted order; first/last pick per-segment row indices (gather
stays exact for any type, including strings); listagg/collect run host-side
per segment (variable-length outputs cannot live on device anyway).

Retract rows (-U/-D): sum and count subtract; ignore-retract drops them for
a field; everything else raises — the same contract as the reference
(FieldAggregator.retract throws UnsupportedOperationException).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..data.batch import Column
from ..types import RowKind
from .merge import MergePlan, pad_to

__all__ = [
    "AggregateSpec",
    "aggregate_merge",
    "AGGREGATORS",
    "segment_reduce",
    "segment_reduce_np",
]

AGGREGATORS = (
    "sum",
    "product",
    "count",
    "max",
    "min",
    "bool_and",
    "bool_or",
    "first_value",
    "first_non_null_value",
    "last_value",
    "last_non_null_value",
    "listagg",
    "collect",
    "merge_map",
    "nested_update",
    "primary-key",
)

_RETRACTABLE = {"sum", "count"}


@dataclass(frozen=True)
class AggregateSpec:
    function: str
    ignore_retract: bool = False
    listagg_delimiter: str = ","
    collect_distinct: bool = False
    nested_key: tuple[str, ...] = ()  # nested_update: ARRAY<ROW> upsert key


@functools.lru_cache(maxsize=None)
def _sum_fn():
    @jax.jit
    def f(perm, seg_id, values, valid, sign):
        m = perm.shape[0]
        v = values[perm]
        ok = valid[perm]
        s = sign[perm]
        contrib = jnp.where(ok, v * s, jnp.zeros((), values.dtype))
        total = jax.ops.segment_sum(contrib, seg_id, num_segments=m)
        any_valid = jax.ops.segment_max(ok.astype(jnp.int32), seg_id, num_segments=m) > 0
        return total, any_valid

    return f


@functools.lru_cache(maxsize=None)
def _minmax_fn(is_max: bool):
    @jax.jit
    def f(perm, seg_id, values, valid):
        m = perm.shape[0]
        v = values[perm]
        ok = valid[perm]
        if is_max:
            fill = jnp.finfo(values.dtype).min if jnp.issubdtype(values.dtype, jnp.floating) else jnp.iinfo(values.dtype).min
            masked = jnp.where(ok, v, fill)
            agg = jax.ops.segment_max(masked, seg_id, num_segments=m)
        else:
            fill = jnp.finfo(values.dtype).max if jnp.issubdtype(values.dtype, jnp.floating) else jnp.iinfo(values.dtype).max
            masked = jnp.where(ok, v, fill)
            agg = jax.ops.segment_min(masked, seg_id, num_segments=m)
        any_valid = jax.ops.segment_max(ok.astype(jnp.int32), seg_id, num_segments=m) > 0
        return agg, any_valid

    return f


@functools.lru_cache(maxsize=None)
def _pick_fn(last: bool):
    @jax.jit
    def f(perm, seg_id, candidate):
        # candidate: (m,) bool in INPUT coords — rows eligible to be picked
        # (validity and/or retract-exclusion already folded in by the caller)
        m = perm.shape[0]
        pos = jnp.arange(m, dtype=jnp.int32)
        ok = candidate[perm]
        if last:
            cand = jnp.where(ok, pos, -1)
            best = jax.ops.segment_max(cand, seg_id, num_segments=m)
        else:
            cand = jnp.where(ok, pos, m)
            best = jax.ops.segment_min(cand, seg_id, num_segments=m)
            best = jnp.where(best == m, -1, best)
        src = jnp.where(best >= 0, perm[jnp.clip(best, 0, m - 1)], -1)
        return src

    return f


def _product_host(plan: MergePlan, values: np.ndarray, eff_valid: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Exact segmented product via np.multiply.reduceat over the sorted order
    (cumprod-ratio tricks on device lose exactness at zeros/int division)."""
    order = plan.perm[plan.valid_sorted]
    v = values.take(order)
    ok = eff_valid.take(order)
    contrib = np.where(ok, v, np.ones((), values.dtype))
    bounds = np.flatnonzero(plan.seg_start[plan.valid_sorted])
    total = np.multiply.reduceat(contrib, bounds)
    any_valid = np.maximum.reduceat(ok.astype(np.int8), bounds) > 0
    return total, any_valid


def _signs(row_kind: np.ndarray, spec: AggregateSpec, dtype) -> tuple[np.ndarray, np.ndarray]:
    """(sign, include) per input row given retract semantics."""
    retract = np.isin(row_kind, (int(RowKind.UPDATE_BEFORE), int(RowKind.DELETE)))
    if spec.ignore_retract:
        return np.ones(len(row_kind), dtype=dtype), ~retract
    if spec.function in _RETRACTABLE:
        sign = np.where(retract, -1, 1).astype(dtype)
        return sign, np.ones(len(row_kind), dtype=np.bool_)
    if retract.any():
        raise ValueError(
            f"aggregate function {spec.function!r} cannot retract; "
            f"use ignore-retract or an input without -U/-D rows"
        )
    return np.ones(len(row_kind), dtype=dtype), np.ones(len(row_kind), dtype=np.bool_)


def aggregate_merge(
    plan: MergePlan,
    column: Column,
    spec: AggregateSpec,
    row_kind: np.ndarray,
) -> Column:
    """Aggregate one value column over the plan's segments. Returns a Column
    of length plan.num_segments (key order)."""
    m, k = plan.m, plan.num_segments
    values = column.values
    valid = column.valid_mask()
    fn = spec.function

    if fn in ("listagg", "collect", "merge_map", "nested_update"):
        return _host_aggregate(plan, values, valid, spec, row_kind)

    if fn == "primary-key":
        # always the latest arrival, null or not, retract rows included
        # (reference FieldPrimaryKeyAgg: agg/retract both return inputField)
        src_idx = _pick_fn(True)(
            jnp.asarray(plan.perm),
            jnp.asarray(plan.seg_id),
            jnp.asarray(pad_to(np.ones(len(values), np.bool_), m, False)),
        )
        return _gather_column(column, np.asarray(src_idx)[:k])

    sign, include = _signs(row_kind, spec, values.dtype if values.dtype != np.dtype(object) else np.int64)
    eff_valid = valid & include

    perm = jnp.asarray(plan.perm)
    seg_id = jnp.asarray(plan.seg_id)

    if fn in ("first_value", "first_non_null_value", "last_value", "last_non_null_value"):
        # *_value picks may land on a null row; *_non_null_value requires
        # validity. Both must respect the retract include-mask.
        candidate = eff_valid if "non_null" in fn else include
        src = _pick_fn(fn.startswith("last"))(perm, seg_id, jnp.asarray(pad_to(candidate, m, False)))
        src = np.asarray(src)[:k]
        return _gather_column(column, src)

    if values.dtype == np.dtype(object):
        raise ValueError(f"aggregate {fn!r} unsupported for string/bytes columns")

    if fn in ("bool_and", "bool_or"):
        v8 = values.astype(np.int8)
        agg, any_valid = _minmax_fn(fn == "bool_or")(
            perm, seg_id, jnp.asarray(pad_to(v8, m, 0)), jnp.asarray(pad_to(eff_valid, m, False))
        )
        out = np.asarray(agg)[:k].astype(np.bool_)
        av = np.asarray(any_valid)[:k]
        return Column(out, av if not av.all() else None)

    if (
        fn in ("max", "min", "sum")
        and values.dtype == np.float64
        and _f64_on_device_unsupported()
    ):
        out, av = _host_reduce(plan, values, eff_valid, fn, sign if fn == "sum" else None)
        return Column(out.astype(values.dtype, copy=False), av if not av.all() else None)
    if fn in ("max", "min"):
        agg, any_valid = _minmax_fn(fn == "max")(
            perm, seg_id, jnp.asarray(pad_to(values, m, 0)), jnp.asarray(pad_to(eff_valid, m, False))
        )
    elif fn == "sum":
        agg, any_valid = _sum_fn()(
            perm,
            seg_id,
            jnp.asarray(pad_to(values, m, 0)),
            jnp.asarray(pad_to(eff_valid, m, False)),
            jnp.asarray(pad_to(sign, m, 1)),
        )
    elif fn == "count":
        ones = np.ones(len(values), dtype=np.int64)
        agg, any_valid = _sum_fn()(
            perm,
            seg_id,
            jnp.asarray(pad_to(ones, m, 0)),
            jnp.asarray(pad_to(eff_valid, m, False)),
            jnp.asarray(pad_to(sign.astype(np.int64), m, 1)),
        )
        out = np.asarray(agg)[:k]
        return Column(out)  # count of nothing is 0, not null
    elif fn == "product":
        out, av = _product_host(plan, values, eff_valid)
        return Column(out.astype(values.dtype, copy=False), av if not av.all() else None)
    else:
        raise ValueError(f"unknown aggregate function {fn!r}; known: {AGGREGATORS}")

    out = np.asarray(agg)[:k].astype(values.dtype, copy=False)
    av = np.asarray(any_valid)[:k]
    return Column(out, av if not av.all() else None)


_DEVICE_FNS = ("sum", "count", "max", "min", "bool_and", "bool_or")
_PICK_FNS = ("first_value", "first_non_null_value", "last_value", "last_non_null_value")


def _f64_on_device_unsupported() -> bool:
    """TPUs have no native f64 ALUs: float64 reductions must stay host-exact
    there (CPU runs them on device under jax x64)."""
    import jax

    return jax.default_backend() == "tpu"


def fused_routable(specs: list[AggregateSpec], columns: list[Column]) -> bool:
    """True when every column can run inside the single fused kernel:
    numeric reductions and first/last picks. product stays host-exact,
    listagg/collect build variable-length host outputs, and f64 reductions
    leave the device path on TPU backends (no native f64)."""
    f64_off_device = _f64_on_device_unsupported()
    for spec, col in zip(specs, columns):
        if spec.function in _PICK_FNS:
            continue
        if spec.function not in _DEVICE_FNS:
            return False
        if col.values.dtype == np.dtype(object):
            return False
        if f64_off_device and col.values.dtype == np.float64 and spec.function != "count":
            return False
    return True


def _host_reduce(plan: MergePlan, values: np.ndarray, eff_valid: np.ndarray, fn: str, sign=None):
    """Exact segmented sum/max/min on host via np reduceat over the sorted
    order (the f64-on-TPU fallback; same pattern as _product_host)."""
    order = plan.perm[plan.valid_sorted]
    v = values.take(order)
    ok = eff_valid.take(order)
    bounds = np.flatnonzero(plan.seg_start[plan.valid_sorted])
    if fn == "sum":
        s = sign.take(order) if sign is not None else np.ones_like(v)
        contrib = np.where(ok, v * s, np.zeros((), v.dtype))
        total = np.add.reduceat(contrib, bounds)
    elif fn == "max":
        contrib = np.where(ok, v, np.full((), -np.inf, v.dtype))
        total = np.maximum.reduceat(contrib, bounds)
    else:  # min
        contrib = np.where(ok, v, np.full((), np.inf, v.dtype))
        total = np.minimum.reduceat(contrib, bounds)
    any_valid = np.maximum.reduceat(ok.astype(np.int8), bounds) > 0
    return total, any_valid


@functools.lru_cache(maxsize=None)
def _fused_aggregate_fn(num_key: int, num_seq: int, col_fns: tuple[str, ...], engine: str = "xla"):
    """Sort + every column's segment reduction in ONE kernel (the aggregation
    analog of the fused dedup kernel): uploads lanes + value columns once,
    downloads only the (C, k) results — no plan arrays, no per-column
    round trips. col_fns entries: sum|count|max|min|bool_and|bool_or|
    pick_first|pick_last."""

    from .merge import pack_selected, sorted_segments

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag, values, valids, signs):
        m = pad_flag.shape[0]
        pad_sorted, perm, _, keep_last, seg_id = sorted_segments(
            num_key, num_seq, key_lanes, seq_lanes, pad_flag, engine=engine
        )
        pos = jnp.arange(m, dtype=jnp.int32)
        outs = []
        anyv = []
        for i, fn in enumerate(col_fns):
            ok = valids[i][perm]
            if fn.startswith("pick_"):
                last = fn == "pick_last"
                if last:
                    cand = jnp.where(ok, pos, -1)
                    best = jax.ops.segment_max(cand, seg_id, num_segments=m)
                else:
                    cand = jnp.where(ok, pos, m)
                    best = jax.ops.segment_min(cand, seg_id, num_segments=m)
                    best = jnp.where(best == m, -1, best)
                outs.append(jnp.where(best >= 0, perm[jnp.clip(best, 0, m - 1)], -1))
                anyv.append(best >= 0)
                continue
            v = values[i][perm]
            if fn in ("sum", "count"):
                s = signs[i][perm].astype(v.dtype)
                contrib = jnp.where(ok, v * s, jnp.zeros((), v.dtype))
                agg = jax.ops.segment_sum(contrib, seg_id, num_segments=m)
            else:
                is_max = fn in ("max", "bool_or")
                if jnp.issubdtype(v.dtype, jnp.floating):
                    fill = jnp.finfo(v.dtype).min if is_max else jnp.finfo(v.dtype).max
                else:
                    fill = jnp.iinfo(v.dtype).min if is_max else jnp.iinfo(v.dtype).max
                masked = jnp.where(ok, v, fill)
                agg = (
                    jax.ops.segment_max(masked, seg_id, num_segments=m)
                    if is_max
                    else jax.ops.segment_min(masked, seg_id, num_segments=m)
                )
            outs.append(agg)
            anyv.append(jax.ops.segment_max(ok.astype(jnp.int32), seg_id, num_segments=m) > 0)
        packed, count = pack_selected(keep_last & (pad_sorted == 0), perm)
        return tuple(outs), tuple(anyv), packed, count

    return f


def fused_aggregate(
    key_lanes: np.ndarray,  # (n, K) uint32
    seq_lanes: np.ndarray | None,
    columns: list[Column],
    specs: list[AggregateSpec],
    row_kind: np.ndarray,
    compress: bool | None = None,
    engine: str = "xla",
) -> tuple[list[Column], np.ndarray]:
    """Single-call aggregation merge over every value column. Returns
    (aggregated columns in key order, last_take winning-row indices). Key
    lanes run through the compression seam (ops/lanes.py) — identical
    segmentation, fewer sort operands."""
    from .merge import prepare_lanes_planned

    klp, slp, pad, n, k, s, m, _plan = prepare_lanes_planned(key_lanes, seq_lanes, compress=compress)
    col_fns = []
    values = []
    valids = []
    signs = []
    for spec, col in zip(specs, columns):
        fn = spec.function
        sign, include = _signs(
            row_kind, spec, col.values.dtype if col.values.dtype != np.dtype(object) else np.int64
        )
        valid = col.valid_mask()
        if fn in _PICK_FNS:
            candidate = (valid & include) if "non_null" in fn else include
            col_fns.append("pick_last" if fn.startswith("last") else "pick_first")
            values.append(np.zeros(m, np.int8))  # unused by picks
            valids.append(pad_to(candidate, m, False))
            signs.append(np.ones(m, np.int8))
        elif fn == "count":
            col_fns.append("count")
            values.append(pad_to(np.ones(n, np.int64), m, 0))
            valids.append(pad_to(valid & include, m, False))
            signs.append(pad_to(sign.astype(np.int8), m, 1))
        elif fn in ("bool_and", "bool_or"):
            col_fns.append(fn)
            values.append(pad_to(col.values.astype(np.int8), m, 0))
            valids.append(pad_to(valid & include, m, False))
            signs.append(np.ones(m, np.int8))
        else:
            col_fns.append(fn)
            values.append(pad_to(col.values, m, 0))
            valids.append(pad_to(valid & include, m, False))
            signs.append(pad_to(sign.astype(np.int8), m, 1))
    if engine == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k + s)
    outs, anyv, packed, count = _fused_aggregate_fn(k, s, tuple(col_fns), engine)(
        klp, slp, pad, tuple(values), tuple(valids), tuple(signs)
    )
    kk = int(count)
    result: list[Column] = []
    for spec, col, fn, o, av in zip(specs, columns, col_fns, outs, anyv):
        if fn.startswith("pick_"):
            result.append(_gather_column(col, np.asarray(o[:kk])))
        elif fn == "count":
            result.append(Column(np.asarray(o[:kk])))  # count of nothing is 0
        else:
            vals = np.asarray(o[:kk]).astype(col.values.dtype, copy=False)
            valid = np.asarray(av[:kk])
            if fn in ("bool_and", "bool_or"):
                vals = vals.astype(np.bool_)
            result.append(Column(vals, valid if not valid.all() else None))
    return result, np.asarray(packed[:kk])


def _gather_column(column: Column, src: np.ndarray) -> Column:
    ok = src >= 0
    safe = np.clip(src, 0, max(len(column) - 1, 0))
    validity = ok & column.valid_mask().take(safe)
    if column.is_code_backed:
        # compressed domain: gather the codes, keep the pool — partial-update
        # and aggregation winners never materialize the strings
        pool, codes = column.dict_cache
        return Column.from_codes(pool, codes.take(safe), validity)
    vals = column.values.take(safe)
    if column.values.dtype != np.dtype(object):
        vals = np.where(validity, vals, np.zeros((), column.values.dtype))
    return Column(vals, validity if not validity.all() else None)


# ---- GROUP BY segment-reduce (ISSUE 16) ---------------------------------
#
# The SQL group-by primitive: group keys arrive as uint32 lanes (dictionary
# codes or narrowed fixed-width values), value columns reduce per segment in
# ONE fused sort+reduce kernel through the same sorted_segments seam the
# merge path uses — pallas/xla/lane-compression all inherit it. Unlike
# aggregate_merge there is no sequence dimension and no retract handling:
# every row contributes, and the caller additionally gets each group's
# minimum input position so first-appearance output order (and distributed
# combines keyed on global row position) stay exact.

_SEGMENT_REDUCE_FNS = ("sum", "count", "min", "max")


@functools.lru_cache(maxsize=None)
def _segment_reduce_fn(num_lanes: int, col_fns: tuple[str, ...], engine: str = "xla"):
    from .merge import pack_selected, sorted_segments

    @jax.jit
    def f(key_lanes, pad_flag, pos, values, valids):
        m = pad_flag.shape[0]
        pad_sorted, perm, seg_start, _keep_last, seg_id = sorted_segments(
            num_lanes, 0, key_lanes, [], pad_flag, engine=engine
        )
        outs = []
        anyv = []
        for i, fn in enumerate(col_fns):
            v = values[i][perm]
            ok = valids[i][perm]
            if fn in ("sum", "count"):
                contrib = jnp.where(ok, v, jnp.zeros((), v.dtype))
                agg = jax.ops.segment_sum(contrib, seg_id, num_segments=m)
            else:
                is_max = fn == "max"
                if jnp.issubdtype(v.dtype, jnp.floating):
                    fill = jnp.finfo(v.dtype).min if is_max else jnp.finfo(v.dtype).max
                else:
                    fill = jnp.iinfo(v.dtype).min if is_max else jnp.iinfo(v.dtype).max
                masked = jnp.where(ok, v, fill)
                agg = (
                    jax.ops.segment_max(masked, seg_id, num_segments=m)
                    if is_max
                    else jax.ops.segment_min(masked, seg_id, num_segments=m)
                )
            outs.append(agg)
            anyv.append(jax.ops.segment_max(ok.astype(jnp.int32), seg_id, num_segments=m) > 0)
        first_pos = jax.ops.segment_min(pos[perm], seg_id, num_segments=m)
        packed, count = pack_selected(seg_start & (pad_sorted == 0), perm)
        return tuple(outs), tuple(anyv), first_pos, packed, count

    return f


def segment_reduce(
    key_lanes: np.ndarray,  # (n, K) uint32
    columns: list[tuple[np.ndarray, np.ndarray | None]],  # (values, valid) per column
    fns: tuple[str, ...],  # sum|count|min|max per column
    pos: np.ndarray | None = None,  # (n,) int64 global row positions
    engine: str = "xla",
    compress: bool | None = None,
):
    """Segment-reduce `columns` over groups keyed by `key_lanes` rows.

    Returns ``(rep, outs, anyv, first_pos)`` with groups in KEY order:
    ``rep[g]`` is the input index of one representative row of group g,
    ``outs[i][g]`` the reduction of column i over group g (masked rows
    contribute identity), ``anyv[i][g]`` whether any row of group g was
    valid for column i, and ``first_pos[g]`` the minimum `pos` over the
    group (first-appearance ordering / distributed combine key).

    Engines: "numpy" routes to the exact host twin; f64 columns leave the
    device on TPU backends (no native f64, same rule as aggregate_merge);
    fully constant key lanes (k == 0 after compression) take the twin too —
    a single group is not worth a device round trip."""
    from .merge import prepare_lanes_planned

    n = int(key_lanes.shape[0])
    if pos is None:
        pos = np.arange(n, dtype=np.int64)
    vals = [(v, np.ones(n, np.bool_) if ok is None else ok) for v, ok in columns]
    if (
        engine == "numpy"
        or n == 0
        or (
            _f64_on_device_unsupported()
            and any(v.dtype == np.float64 for v, _ in vals)
        )
    ):
        return segment_reduce_np(key_lanes, vals, fns, pos)
    klp, slp, pad, _n, k, s, m, _plan = prepare_lanes_planned(key_lanes, None, compress=compress)
    if k == 0:
        return segment_reduce_np(key_lanes, vals, fns, pos)
    from ..metrics import sql_metrics

    sql_metrics().counter("rows_reduced_device").inc(n)
    if engine == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k)
    big = np.iinfo(np.int64).max
    outs, anyv, first_pos, packed, count = _segment_reduce_fn(k, tuple(fns), engine)(
        klp,
        pad,
        jnp.asarray(pad_to(pos.astype(np.int64, copy=False), m, big)),
        tuple(jnp.asarray(pad_to(v, m, 0)) for v, _ in vals),
        tuple(jnp.asarray(pad_to(ok, m, False)) for _, ok in vals),
    )
    g = int(count)
    return (
        np.asarray(packed[:g]),
        [np.asarray(o[:g]).astype(v.dtype, copy=False) for o, (v, _) in zip(outs, vals)],
        [np.asarray(a[:g]) for a in anyv],
        np.asarray(first_pos[:g]),
    )


def segment_reduce_np(
    key_lanes: np.ndarray,
    columns: list[tuple[np.ndarray, np.ndarray]],
    fns: tuple[str, ...],
    pos: np.ndarray,
):
    """Exact numpy twin of segment_reduce: lexsort + reduceat, identical
    output contract (groups in key order)."""
    n = int(key_lanes.shape[0])
    if n == 0:
        return (
            np.zeros(0, np.int64),
            [np.zeros(0, v.dtype) for v, _ in columns],
            [np.zeros(0, np.bool_) for _ in columns],
            np.zeros(0, np.int64),
        )
    kk = key_lanes.shape[1]
    order = np.lexsort(tuple(key_lanes[:, i] for i in range(kk - 1, -1, -1)))
    sk = key_lanes[order]
    neq = (sk[1:] != sk[:-1]).any(axis=1) if n > 1 else np.zeros(0, np.bool_)
    starts = np.flatnonzero(np.concatenate([[True], neq]))
    outs = []
    anyv = []
    for (v, ok), fn in zip(columns, fns):
        vs = v[order]
        oks = ok[order]
        if fn in ("sum", "count"):
            contrib = np.where(oks, vs, np.zeros((), v.dtype))
            outs.append(np.add.reduceat(contrib, starts))
        elif fn == "max":
            fill = np.finfo(v.dtype).min if v.dtype.kind == "f" else np.iinfo(v.dtype).min
            outs.append(np.maximum.reduceat(np.where(oks, vs, fill), starts))
        else:
            fill = np.finfo(v.dtype).max if v.dtype.kind == "f" else np.iinfo(v.dtype).max
            outs.append(np.minimum.reduceat(np.where(oks, vs, fill), starts))
        anyv.append(np.maximum.reduceat(oks.astype(np.int8), starts) > 0)
    first_pos = np.minimum.reduceat(pos[order], starts)
    return order[starts], outs, anyv, first_pos


def _host_aggregate(plan: MergePlan, values, valid, spec: AggregateSpec, row_kind) -> Column:
    """listagg / collect / merge_map / nested_update: variable-length or
    structured outputs, built per segment on host from the sorted order
    (still no comparator loops — slicing only)."""
    k = plan.num_segments
    order = plan.perm[plan.valid_sorted]
    v_sorted = values.take(order)
    ok_sorted = valid.take(order)
    retract = np.isin(row_kind, (int(RowKind.UPDATE_BEFORE), int(RowKind.DELETE))).take(order)
    if spec.ignore_retract:
        ok_sorted = ok_sorted & ~retract
        retract = np.zeros_like(retract)
    elif retract.any() and spec.function == "listagg":
        raise ValueError("listagg cannot retract; configure ignore-retract")
    bounds = np.flatnonzero(plan.seg_start[plan.valid_sorted])
    out = np.empty(k, dtype=object)
    validity = np.zeros(k, dtype=np.bool_)
    for s in range(k):
        lo = bounds[s]
        hi = bounds[s + 1] if s + 1 < k else len(order)
        if spec.function == "merge_map":
            out[s], validity[s] = _merge_map_segment(v_sorted, ok_sorted, retract, lo, hi)
            continue
        if spec.function == "nested_update":
            out[s], validity[s] = _nested_update_segment(
                v_sorted, ok_sorted, retract, lo, hi, spec.nested_key
            )
            continue
        if spec.function == "listagg":
            vals = [v_sorted[i] for i in range(lo, hi) if ok_sorted[i]]
            if vals:
                out[s] = spec.listagg_delimiter.join(str(x) for x in vals)
                validity[s] = True
        else:  # collect
            vals = []
            for i in range(lo, hi):
                if not ok_sorted[i]:
                    continue
                x = v_sorted[i]
                # an input may be a raw scalar OR an already-collected list
                # (a stored row re-merged with new arrivals): flatten lists so
                # re-aggregation is associative (reference FieldCollectAgg
                # concatenates array inputs)
                items = list(x) if isinstance(x, (list, tuple)) else [x]
                if retract[i]:
                    # reference FieldCollectAgg removes matching elements
                    for item in items:
                        if item in vals:
                            vals.remove(item)
                else:
                    vals.extend(items)
            if spec.collect_distinct:
                seen = []
                for x in vals:
                    if x not in seen:
                        seen.append(x)
                vals = seen
            out[s] = vals
            validity[s] = True
    return Column(out, validity if not validity.all() else None)


def _merge_map_segment(v_sorted, ok_sorted, retract, lo, hi):
    """Dict union in (key, seq) order; null inputs keep the accumulator;
    retract rows remove their keys (reference FieldMergeMapAgg)."""
    acc = None
    for i in range(lo, hi):
        if not ok_sorted[i]:
            continue
        m = v_sorted[i]
        if retract[i]:
            if acc:
                for key in dict(m):
                    acc.pop(key, None)
            continue
        if acc is None:
            acc = dict(m)
        else:
            acc.update(m)
    return acc, acc is not None


def _row_key(row, nested_key):
    if isinstance(row, dict):
        return tuple(row.get(f) for f in nested_key)
    return tuple(row)  # full-row identity when no key configured


def _nested_update_segment(v_sorted, ok_sorted, retract, lo, hi, nested_key):
    """ARRAY<ROW> upsert: concat in order; with a nested key, later rows
    replace earlier rows sharing the key; retract rows remove matching
    elements (reference FieldNestedUpdateAgg)."""
    acc = None
    for i in range(lo, hi):
        if not ok_sorted[i]:
            continue
        rows = v_sorted[i] or []
        if retract[i]:
            if acc:
                if nested_key:
                    dead = {_row_key(r, nested_key) for r in rows}
                    acc = [r for r in acc if _row_key(r, nested_key) not in dead]
                else:
                    for r in rows:
                        if r in acc:
                            acc.remove(r)
            continue
        if acc is None:
            acc = list(rows)
        else:
            acc.extend(rows)
    if acc is not None and nested_key:
        by_key = {}
        for r in acc:
            by_key[_row_key(r, nested_key)] = r  # last wins
        acc = list(by_key.values())
    return acc, acc is not None
