"""Sort + segment + select: the device merge kernel.

Replaces the reference's SortMergeReader heap loop and MergeFunction
application (/root/reference/paimon-core/.../mergetree/compact/
SortMergeReaderWithMinHeap.java:54-70 orders by (userKey, udsSeq, seqNumber);
:167-177 feeds same-key groups to the merge function). Here the ordering is
one stable lexicographic `lax.sort` and the per-key group logic is masks and
segment reductions — no data-dependent control flow, fully XLA-fusable.

Coordinate systems: "input" = row index into the concatenated runs;
"sorted" = position after the sort. `perm` maps sorted -> input.

Shapes: every device array is padded to a power-of-two bucket `m` so XLA
compiles once per (lane arity, size bucket). Pad rows carry a set pad flag
(the most significant sort lane), so valid rows occupy sorted slots [0, n)
and pad rows segment separately. The only dynamic-shape step — boolean
keep-mask -> index compaction — happens host-side in numpy where it's free.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..types import RowKind

__all__ = [
    "MergePlan",
    "merge_plan",
    "pad_size",
    "deduplicate_take",
    "first_row_take",
    "partial_update_takes",
]

_MIN_PAD = 128


def pad_size(n: int) -> int:
    """Next power of two (>=128): bounds the jit cache to O(log n) entries."""
    p = _MIN_PAD
    while p < n:
        p <<= 1
    return p


def pad_to(arr: np.ndarray, m: int, fill=0) -> np.ndarray:
    out = np.full((m,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def sorted_segments(
    num_key_lanes: int, num_seq_lanes: int, key_lanes, seq_lanes, pad_flag, extra_keys=(), engine: str = "xla"
):
    """The shared in-kernel preamble (traced inside each jitted kernel): one
    stable lexicographic sort on (pad, key lanes, seq lanes, iota), then
    segment detection over (pad, key lanes) only — sequence lanes do NOT
    split segments (same key, different seq = one merge group). Returns
    (sorted_pad, perm, seg_start, keep_last, seg_id).

    Lane containers may be a (L, m) array OR a list of (m,) arrays of MIXED
    uint dtypes (the range-narrowed upload path) — per-lane indexing and
    per-lane compares avoid any cross-dtype stack.

    extra_keys: order-consistent leading key lanes (the offset-value code
    lane of ops/lanes.py) sorted between the pad flag and the key lanes and
    tested FIRST in boundary detection. An extra key must satisfy the OVC
    contract — where it differs it agrees with full-key order, where it ties
    the key lanes decide — so both the permutation and the segmentation stay
    bit-identical to the plain path.

    engine="pallas" is the sort-engine=pallas seam every merge kernel
    inherits: batches that pass the VMEM admission test run the FUSED
    pallas kernel (sort + boundary + keep-last in one pass,
    ops/pallas_kernels.fused_sort_segments); larger batches keep `lax.sort`
    but compute the boundary mask with the pallas sweep kernel. Both tiers
    are bit-identical to the plain path; when pallas is unavailable the
    engine silently degrades to xla."""
    m = pad_flag.shape[0]
    extra = list(extra_keys)
    boundary = [pad_flag] + extra + [key_lanes[i] for i in range(num_key_lanes)]
    order = [seq_lanes[i] for i in range(num_seq_lanes)]
    if engine == "pallas":
        from . import pallas_kernels as pk

        if pk.fusable(m, len(boundary) + len(order)):
            return pk.fused_sort_segments(boundary, order)
        if not pk._PALLAS_OK:
            engine = "xla"  # automatic fallback: no pallas in this build
    iota = jnp.arange(m, dtype=jnp.int32)
    operands = boundary + order + [iota]
    out = jax.lax.sort(operands, num_keys=len(operands) - 1, is_stable=True)
    perm = out[-1]
    if engine == "pallas":
        # large-batch tier: lax.sort + the fused pallas boundary sweep
        # (narrowed lanes may be u8/u16 — widening on device costs nothing)
        from .pallas_kernels import keep_last_mask, pallas_interpret

        stacked = jnp.stack(
            [lane.astype(jnp.uint32) for lane in out[: len(boundary)]], axis=0
        )
        keep_last = keep_last_mask(stacked, interpret=pallas_interpret(), mask_pad=False).astype(
            jnp.bool_
        )
        seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), keep_last[:-1]])
        seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
        return out[0], perm, seg_start, keep_last, seg_id
    neq = jnp.zeros(m - 1, dtype=jnp.bool_)
    for lane in out[: len(boundary)]:
        neq = neq | (lane[1:] != lane[:-1])
    seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
    keep_last = jnp.concatenate([neq, jnp.ones((1,), jnp.bool_)])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    return out[0], perm, seg_start, keep_last, seg_id


def segment_last_where(seg_id, masks, pos=None):
    """In-kernel: per SEGMENT, the last sorted position where each mask row
    is True (-1 = none). masks (F, m) bool in SORTED coords; returns (F, m)
    indexed by segment id. The shared core of every partial-update selection
    (local fused, local planned, and the distributed range-shuffle engine)."""
    m = seg_id.shape[0]
    if pos is None:
        pos = jnp.arange(m, dtype=jnp.int32)
    cand = jnp.where(masks, pos[None, :], -1)
    return jax.vmap(lambda c: jax.ops.segment_max(c, seg_id, num_segments=m))(cand)


def pack_selected(sel, perm):
    """In-kernel: pack the selected perms to the front (key order) and count
    them — the minimal device->host transfer for selection kernels."""
    not_sel = (~sel).astype(jnp.uint32)
    _, packed = jax.lax.sort([not_sel, perm], num_keys=1, is_stable=True)
    return packed, sel.sum()


def _runid_bits(num_runs: int) -> int:
    """Bits per run-id in the compact selection encoding."""
    if num_runs <= 4:
        return 2
    if num_runs <= 16:
        return 4
    return 8


def _bitpack_rows(vals, rbits: int):
    """In-kernel: pack small uints (< 2^rbits) along the last axis,
    8/rbits per byte — the device half of _unpack_runids."""
    per = 8 // rbits
    r2 = vals.astype(jnp.uint8).reshape(vals.shape[:-1] + (vals.shape[-1] // per, per))
    byte = r2[..., 0]
    for i in range(1, per):
        byte = byte | (r2[..., i] << jnp.uint8(i * rbits))
    return byte


def _unpack_runids(packed: np.ndarray, c: int, rbits: int) -> np.ndarray:
    """Host: first c rbits-wide values from a _bitpack_rows byte stream."""
    per = 8 // rbits
    pk = np.asarray(packed[: (c + per - 1) // per])
    if rbits == 8:
        return pk[:c]
    lanes = [(pk >> (i * rbits)) & ((1 << rbits) - 1) for i in range(per)]
    return np.stack(lanes, axis=1).ravel()[:c]


def _interleave_winners(winners: np.ndarray, rs: np.ndarray) -> np.ndarray:
    """Host: winners are grouped by run/block (ascending within each); rs is
    the run-id per output position. The stable argsort maps output positions
    ordered (run, output-order) onto winners element for element — radix
    argsort over small ints is O(c)."""
    out = np.empty(len(rs), dtype=np.int32)
    out[np.argsort(rs, kind="stable")] = winners
    return out


def pack_selection_compact(sel, perm, starts):
    """In-kernel epilogue: encode the selection as (a) a bit-packed keep-mask
    in INPUT coordinates and (b) bit-packed run-ids of the winners in key
    order. On a downlink-bound rig this shrinks the dominant device->host
    transfer ~10x vs int32 winner indices (m/8 bytes + c*rbits/8 bytes vs
    4c bytes); the host reconstructs the exact indices with O(c) numpy
    (unpack_selection_compact). Correctness rests on runs being key-sorted:
    within one run, winners ascend in both key and input index, so the
    keep-mask fixes each run's winner set and the run-id sequence fixes the
    interleave."""
    m = perm.shape[0]
    sel_input = jnp.zeros((m,), jnp.bool_).at[perm].set(sel)
    mask_bytes = jnp.packbits(sel_input)
    run_in = jnp.clip(
        jnp.searchsorted(starts, perm, side="right").astype(jnp.int32) - 1,
        0,
        starts.shape[0] - 1,
    )
    _, runs_key_order = jax.lax.sort(
        [(~sel).astype(jnp.uint32), run_in.astype(jnp.uint32)], num_keys=1, is_stable=True
    )
    byte = _bitpack_rows(runs_key_order, _runid_bits(starts.shape[0]))
    return mask_bytes, byte, sel.sum()


def unpack_selection_compact(mask_bytes, runs_packed, count, n: int, num_runs: int, rbits: int) -> np.ndarray:
    """Host half of pack_selection_compact: (bit mask, packed run-ids, count)
    -> selected input-row indices in global key order. Downloads only
    ceil(n/8) + ceil(c*rbits/8) bytes off the device. rbits comes from the
    dispatch handle (single source: _runid_bits over the padded starts the
    kernel actually saw)."""
    c = int(count)
    if c == 0:
        return np.empty(0, dtype=np.int32)
    keep = np.unpackbits(np.asarray(mask_bytes[: (n + 7) // 8]), count=n).astype(bool)
    winners = np.flatnonzero(keep).astype(np.int32)  # grouped by run, ascending
    if num_runs <= 1:
        return winners
    return _interleave_winners(winners, _unpack_runids(runs_packed, c, rbits))


def narrow_lane(col: np.ndarray) -> np.ndarray:
    """Range-narrow one u32 lane for upload: subtract the min (a constant
    shift preserves order and segment boundaries) and downcast to u16 when
    the value range strictly fits (the dtype max is reserved as the pad
    sentinel). On a link-bound rig this halves lane bytes — the common case:
    dense ids, dictionary ranks, bucket-local sequence numbers.

    Deliberately TWO tiers only (u16/u32, no u8): each distinct dtype combo
    is a separate jit signature, so tiers trade link bytes against compile
    cache entries (2^(k+s) worst case; the persistent compile cache makes
    each a one-time cost). A batch whose range hovers around the u16
    boundary can flap tiers between merges — acceptable with the disk cache,
    revisit if profiles show recompile churn."""
    if col.size == 0:
        return col
    lo = col.min()
    ptp = int(col.max()) - int(lo)
    if ptp < np.iinfo(np.uint16).max:  # strict: sentinel must sort after
        return (col - lo).astype(np.uint16)
    return (col - lo).astype(np.uint32)


def prepare_lanes(key_lanes: np.ndarray, seq_lanes: np.ndarray | None, narrow: bool = True):
    """The shared host-side prep: drop constant lanes, range-narrow each
    remaining lane (u16 upload when the value range allows — the link is
    the bottleneck on tunnel-attached chips), pad rows to the power-of-two
    bucket with max-sentinel keys + pad flags. Returns
    (klp, slp, pad, n, num_key, num_seq, m) where klp/slp are LISTS of (m,)
    arrays of possibly-mixed uint dtypes (not 2-D matrices — lanes narrow
    independently) and pad is (m,) u8."""
    key_lanes = np.ascontiguousarray(key_lanes)
    kl = drop_constant_lanes(key_lanes)
    sl = drop_constant_lanes(np.ascontiguousarray(seq_lanes)) if seq_lanes is not None else None
    n, k = kl.shape
    s = 0 if sl is None else sl.shape[1]
    m = pad_size(n)
    key_cols = [narrow_lane(kl[:, i]) if narrow else kl[:, i] for i in range(k)]
    klp = [np.full(m, np.iinfo(c.dtype).max, dtype=c.dtype) for c in key_cols]
    for buf, c in zip(klp, key_cols):
        buf[:n] = c
    seq_cols = [narrow_lane(sl[:, i]) if narrow else sl[:, i] for i in range(s)]
    slp = [np.zeros(m, dtype=c.dtype) for c in seq_cols]
    for buf, c in zip(slp, seq_cols):
        buf[:n] = c
    pad = np.zeros(m, dtype=np.uint8)
    pad[n:] = 1
    return klp, slp, pad, n, k, s, m


def prepare_lanes_planned(
    key_lanes: np.ndarray,
    seq_lanes: np.ndarray | None,
    narrow: bool = True,
    compress: bool | None = None,
):
    """prepare_lanes behind the key-lane compression seam (ops/lanes.py):
    the key matrix is truncated/packed per a LanePlan before the usual
    narrowing + padding. Returns (klp, slp, pad, n, k, s, m, plan); plan is
    None when the layer is off (k then counts post-drop_constant_lanes key
    lanes, exactly the legacy path). Either way an all-constant key yields
    k == 0 — callers take the zero-width scalar fast path instead of the old
    dummy-lane sort."""
    import dataclasses

    from .lanes import compress_key_lanes

    kl, plan = compress_key_lanes(np.ascontiguousarray(key_lanes), compress)
    klp, slp, pad, n, k, s, m = prepare_lanes(kl, seq_lanes, narrow=narrow)
    if plan is not None and plan.use_ovc and kl.shape[0]:
        # narrow_lane min-shifts every uploaded column; the OVC base must
        # shift identically so the in-kernel lane==base compares match the
        # packed-space comparison exactly (a shared constant shift per column
        # preserves ==, <, and the code's value-field bound)
        if narrow:
            mins = kl.min(axis=0)
            plan = dataclasses.replace(
                plan, base=tuple(int(b) - int(mn) for b, mn in zip(plan.base, mins))
            )
    return klp, slp, pad, n, k, s, m, plan


@functools.lru_cache(maxsize=None)
def _plan_fn(num_key_lanes: int, num_seq_lanes: int, ovc_vbits: int = 0, engine: str = "xla"):
    """Builds the jitted sort+segment kernel for a lane arity. ovc_vbits > 0
    adds the device-computed offset-value code as the leading key (and the
    base values as a traced (G,) operand). engine routes the preamble
    through the sort-engine seam (pallas = fused sort+segment kernel)."""
    if ovc_vbits:
        from .lanes import ovc_codes_jax

        @jax.jit
        def f_ovc(key_lanes, seq_lanes, pad_flag, base):
            code = ovc_codes_jax(
                [key_lanes[i] for i in range(num_key_lanes)], base, ovc_vbits
            )
            _, perm, seg_start, keep_last, seg_id = sorted_segments(
                num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag,
                extra_keys=(code,), engine=engine,
            )
            return perm, seg_start, keep_last, seg_id

        return f_ovc

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag):
        # key/seq lanes: (K, m)/(S, m) arrays OR lists of (m,) mixed-dtype
        # uint arrays (narrowed upload); pad_flag: (m,) uint
        _, perm, seg_start, keep_last, seg_id = sorted_segments(
            num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag, engine=engine
        )
        return perm, seg_start, keep_last, seg_id

    return f


@dataclass
class MergePlan:
    """Sorted view of the concatenated inputs of one merge. Arrays have
    padded length m; valid rows occupy sorted slots [0, n)."""

    perm: np.ndarray  # (m,) sorted -> input row index (int32)
    seg_start: np.ndarray  # (m,) bool, sorted coords
    keep_last: np.ndarray  # (m,) bool, sorted coords (last row of segment)
    seg_id: np.ndarray  # (m,) int32, sorted coords
    n: int  # valid rows
    m: int  # padded size

    @property
    def valid_sorted(self) -> np.ndarray:
        return np.arange(self.m) < self.n

    @property
    def num_segments(self) -> int:
        """Segments holding valid rows (pad segments sort after them)."""
        return int(self.seg_id[self.n - 1]) + 1 if self.n else 0


def drop_constant_lanes(lanes: np.ndarray) -> np.ndarray:
    """A lane equal everywhere affects neither ordering nor segmentation —
    dropping it shrinks host->device transfer and sort width (the common case:
    int64 keys/seqnos whose high 32 bits are constant within one merge)."""
    n, k = lanes.shape
    if n <= 1 or k == 0:
        return lanes
    keep = [i for i in range(k) if lanes[0, i] != lanes[-1, i] or (lanes[:, i] != lanes[0, i]).any()]
    if len(keep) == k:
        return lanes
    return lanes[:, keep] if keep else lanes[:, :0]


def merge_plan(
    key_lanes: np.ndarray,
    seq_lanes: np.ndarray | None = None,
    compress: bool | None = None,
    engine: str = "xla",
) -> MergePlan:
    """key_lanes: (n, K) uint32. seq_lanes: (n, S) uint32 ordering within a
    key group (user-defined sequence lanes first, then sequence-number lanes —
    the reference's (udsSeq, seqNumber) tie-break). Stable: remaining ties
    resolve to input order, which is run order — same as the heap's reader
    index tie-break.

    Callers whose input rows are already seq-ascending within equal keys
    (runs with disjoint seq ranges concatenated in seq order) may pass
    seq_lanes=None: stability makes explicit sequence lanes redundant.

    compress routes the key matrix through the lane-compression layer
    (ops/lanes.py: truncation + packing + OVC) — bit-identical plan, fewer
    sort operands; None resolves to the merge.lane-compression default."""
    from .lanes import compress_key_lanes, resolve_compress

    key_lanes = np.ascontiguousarray(key_lanes)
    seq_keep = drop_constant_lanes(np.ascontiguousarray(seq_lanes)) if seq_lanes is not None else None
    if resolve_compress(compress):
        kl_kept, plan = compress_key_lanes(key_lanes, True)
    else:
        kl_kept, plan = drop_constant_lanes(key_lanes), None
    if kl_kept.shape[1] == 0 and (seq_keep is None or seq_keep.shape[1] == 0):
        # all keys equal (or no rows) and nothing to order by: the zero-width
        # scalar fast path — one segment of valid rows in input order, no
        # sort dispatched at all (the old path kept a dummy constant lane
        # "for shape sanity" and sorted it anyway)
        return _scalar_plan(key_lanes.shape[0])
    return _merge_plan_padded(kl_kept, seq_keep, plan, engine)


def _scalar_plan(n: int) -> MergePlan:
    """Host-built MergePlan for the zero-width key, zero seq-lane case: the
    stable sort of (pad, iota) is the identity, valid rows form one segment
    and pads another — exactly what the k=0 kernel would return, without the
    device trip."""
    m = pad_size(n)
    perm = np.arange(m, dtype=np.int32)
    seg_start = np.zeros(m, dtype=np.bool_)
    seg_start[0] = True
    keep_last = np.zeros(m, dtype=np.bool_)
    keep_last[m - 1] = True
    if 0 < n < m:
        seg_start[n] = True
        keep_last[n - 1] = True
    seg_id = (np.cumsum(seg_start) - 1).astype(np.int32)
    return MergePlan(perm=perm, seg_start=seg_start, keep_last=keep_last, seg_id=seg_id, n=n, m=m)


def _merge_plan_padded(
    key_lanes: np.ndarray, seq_lanes: np.ndarray | None, plan=None, engine: str = "xla"
) -> MergePlan:
    n, k = key_lanes.shape
    if seq_lanes is None:
        seq_lanes = np.zeros((n, 0), dtype=np.uint32)
    s = seq_lanes.shape[1]
    m = pad_size(n)
    kl = np.full((k, m), 0xFFFFFFFF, dtype=np.uint32)
    kl[:, :n] = key_lanes.T
    sl = np.zeros((s, m), dtype=np.uint32)
    sl[:, :n] = seq_lanes.T
    pad = np.zeros(m, dtype=np.uint32)
    pad[n:] = 1
    use_ovc = plan is not None and plan.use_ovc
    timer = None
    if engine == "pallas":
        from ..metrics import pallas_metrics, timed
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k + s + (1 if use_ovc else 0))
        # this path resolves synchronously just below (np.asarray), so the
        # wall time around dispatch+download is the kernel latency
        timer = timed(pallas_metrics().histogram("kernel_ms"))
        timer.__enter__()
    if use_ovc:
        # this path uploads unshifted u32 lanes, so the packed-space base
        # passes through unshifted too
        perm, seg_start, keep_last, seg_id = _plan_fn(k, s, plan.ovc_vbits, engine)(
            kl, sl, pad, np.asarray(plan.base, dtype=np.uint32)
        )
    else:
        perm, seg_start, keep_last, seg_id = _plan_fn(k, s, 0, engine)(kl, sl, pad)
    if timer is not None:
        np.asarray(perm)  # force the async dispatch before stopping the clock
        timer.__exit__(None, None, None)
    return MergePlan(
        perm=np.asarray(perm),
        seg_start=np.asarray(seg_start),
        keep_last=np.asarray(keep_last),
        seg_id=np.asarray(seg_id),
        n=n,
        m=m,
    )


def deduplicate_take(plan: MergePlan) -> np.ndarray:
    """Input-row indices of each key's last (key, seq) row — the deduplicate
    merge engine (reference DeduplicateMergeFunction.java:31: last row wins).
    Output is in key order."""
    return plan.perm[plan.keep_last & plan.valid_sorted]


@functools.lru_cache(maxsize=None)
def _dedup_select_fn(num_key_lanes: int, num_seq_lanes: int, backend: str = "xla", ovc_vbits: int = 0):
    """Sort + keep-last + device-side compaction: returns ONLY the selected
    input indices (packed to the front) and their count — the minimal
    device->host transfer for the dominant dedup path. backend="pallas"
    runs the fused pallas sort+segment kernel (or the lax.sort + pallas
    boundary sweep above the VMEM cap) through the sorted_segments seam;
    ovc_vbits > 0 computes the offset-value code lane on device and leads
    the sort + boundary detection with it (ops/lanes.py) — composing with
    either engine."""
    if ovc_vbits:
        from .lanes import ovc_codes_jax

        @jax.jit
        def f_ovc(key_lanes, seq_lanes, pad_flag, base):
            code = ovc_codes_jax(
                [key_lanes[i] for i in range(num_key_lanes)], base, ovc_vbits
            )
            pad_sorted, perm, _, keep_last, _ = sorted_segments(
                num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag,
                extra_keys=(code,), engine=backend,
            )
            return pack_selected(keep_last & (pad_sorted == 0), perm)

        return f_ovc

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag):
        pad_sorted, perm, _, keep_last, _ = sorted_segments(
            num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag, engine=backend
        )
        sel = keep_last & (pad_sorted == 0)  # exclude pad rows
        return pack_selected(sel, perm)

    return f


def deduplicate_select_async(
    key_lanes: np.ndarray,
    seq_lanes: np.ndarray | None = None,
    backend: str = "xla",
    compress: bool | None = None,
):
    """Dispatch the dedup kernel without blocking: returns (packed_device,
    count_device). jax's async dispatch lets the host keep decoding value
    columns while the device sorts — resolve with deduplicate_resolve().
    The key matrix goes through the lane-compression seam first; an
    all-constant key short-circuits to the scalar winner without any device
    dispatch."""
    klp, slp, pad, n, k, s, m, plan = prepare_lanes_planned(key_lanes, seq_lanes, compress=compress)
    if k == 0:
        # all keys equal: one winner — the last row in (seq, input) order;
        # no key sort, no device trip (host lexsort of the seq lanes only)
        from .lanes import scalar_dedup_winner

        return ("scalar", scalar_dedup_winner(seq_lanes, n))
    use_ovc = plan is not None and plan.use_ovc
    if backend == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k + s + (1 if use_ovc else 0))
    if use_ovc:
        return _dedup_select_fn(k, s, backend, plan.ovc_vbits)(
            klp, slp, pad, np.asarray(plan.base, dtype=np.uint32)
        )
    return _dedup_select_fn(k, s, backend)(klp, slp, pad)


def _link_encodings_pay_off() -> bool:
    """Compact/delta selection encodings trade device+host pack/unpack work
    for link bytes. On the CPU backend there IS no link — "device" arrays
    are host memory — so the encodings are pure overhead (they were part of
    the r03 CPU-fallback bench regression). PAIMON_TPU_FORCE_COMPACT=1
    overrides so tests exercise the device dispatch policy on CPU.

    Once a backend is LIVE this asks it directly (covers jax's silent
    fall-through from an unreachable accelerator to cpu in a platform list
    like "axon,cpu"). Before any backend exists it reads only the
    CONFIGURED platform — never `jax.default_backend()`, which initializes
    the backend, and on a wedged tunnel an accelerator-platform init blocks
    indefinitely; dispatch policy must not be the call that first touches
    the device. (Worst case: the first dispatch of a process guesses from
    config, every later one sees the real backend.)"""
    if os.environ.get("PAIMON_TPU_FORCE_COMPACT", "") == "1":
        return True
    return not resolved_platform_is_cpu()


def resolved_platform_is_cpu() -> bool:
    """Best platform answer available WITHOUT initializing a backend (policy
    code must never be the first backend-touching call — a wedged-tunnel
    accelerator init blocks indefinitely). Once a backend is live this asks
    it directly (covers jax's silent fall-through from an unreachable
    accelerator to cpu in a platform list like "axon,cpu"); before that it
    reads only the CONFIGURED platform."""
    try:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):  # already initialized: safe to ask
            return jax.default_backend() == "cpu"
    except Exception:
        pass
    cfg = getattr(jax.config, "jax_platforms", None) or os.environ.get("JAX_PLATFORMS", "")
    return str(cfg).split(",")[0] == "cpu"


def _real_starts(run_offsets: Sequence[int]) -> list[int]:
    """Start offsets of the NON-EMPTY runs (a filtered-out file yields a
    duplicate offset) — the single source for run filtering shared by the
    wide-compact and delta-packed paths."""
    starts = [s for s, e in zip(run_offsets[:-1], run_offsets[1:]) if e > s]
    return starts or [0]


def _pad_starts(starts_real: Sequence[int], m: int) -> np.ndarray:
    """Pad run starts to a pow2 length (min 4) so jit signatures stay
    bounded; pad entries point past the end (m) and thus never win a
    searchsorted. The padded length also fixes the run-id bit width
    (_runid_bits) on both device and host."""
    rp = 4
    while rp < len(starts_real):
        rp <<= 1
    out = np.full(rp, m, dtype=np.int32)
    out[: len(starts_real)] = starts_real
    return out


@functools.lru_cache(maxsize=None)
def _dedup_select_compact_fn(num_key_lanes: int, num_seq_lanes: int, ovc_vbits: int = 0, engine: str = "xla"):
    """Sort + keep-last + compact-encoded selection: the downlink-minimal
    dedup kernel (bit-packed keep-mask + run-id interleave instead of int32
    indices). ovc_vbits > 0 leads sort + boundary detection with the
    device-computed offset-value code lane; engine routes the preamble
    through the sort-engine seam."""
    if ovc_vbits:
        from .lanes import ovc_codes_jax

        @jax.jit
        def f_ovc(key_lanes, seq_lanes, pad_flag, starts, base):
            code = ovc_codes_jax(
                [key_lanes[i] for i in range(num_key_lanes)], base, ovc_vbits
            )
            pad_sorted, perm, _, keep_last, _ = sorted_segments(
                num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag,
                extra_keys=(code,), engine=engine,
            )
            return pack_selection_compact(keep_last & (pad_sorted == 0), perm, starts)

        return f_ovc

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag, starts):
        pad_sorted, perm, _, keep_last, _ = sorted_segments(
            num_key_lanes, num_seq_lanes, key_lanes, seq_lanes, pad_flag, engine=engine
        )
        sel = keep_last & (pad_sorted == 0)
        return pack_selection_compact(sel, perm, starts)

    return f


def deduplicate_select_compact_async(
    key_lanes: np.ndarray, run_offsets: Sequence[int], compress: bool | None = None, backend: str = "xla"
):
    """Compact-download dispatch for run-structured inputs (each run
    key-sorted ascending). Returns an opaque handle for
    deduplicate_resolve(), or None above 256 runs (run-ids are u8 on
    device; the caller falls back to the index-download path). Requires no
    explicit seq lanes (run order + sort stability carries the sequence
    tie-break)."""
    starts_real = _real_starts(run_offsets)
    if len(starts_real) > 256:
        return None  # run-ids are u8 on device
    klp, slp, pad, n, k, s, m, plan = prepare_lanes_planned(key_lanes, None, compress=compress)
    if k == 0:
        from .lanes import scalar_dedup_winner

        return ("scalar", scalar_dedup_winner(None, n))
    starts_p = _pad_starts(starts_real, m)
    use_ovc = plan is not None and plan.use_ovc
    if backend == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k + s + (1 if use_ovc else 0))
    if use_ovc:
        outs = _dedup_select_compact_fn(k, s, plan.ovc_vbits, backend)(
            klp, slp, pad, starts_p, np.asarray(plan.base, dtype=np.uint32)
        )
    else:
        outs = _dedup_select_compact_fn(k, s, 0, backend)(klp, slp, pad, starts_p)
    return ("compact", outs, n, len(starts_real), _runid_bits(len(starts_p)))


def pack_delta_runs(col: np.ndarray, run_offsets: Sequence[int]):
    """Delta-pack one u32 lane of ascending key-sorted runs for upload:
    u16 within-run deltas + per-run u32 bases; the device reconstructs the
    lane exactly with one cumsum. Halves the dominant link bytes for dense
    keys (the VERDICT r2 #2 'delta/bit-packed lane upload'). Returns
    (deltas u16 (m,), starts i32 (R,), bases u32 (R,), pad u8 (m,), n, m,
    num_real_runs) or None when any within-run delta exceeds u16 (caller
    falls back wide)."""
    n = len(col)
    if n == 0:
        return None
    if int(col.max()) - int(col.min()) < 0xFFFF:
        # the whole range fits u16: narrow_lane's wide path already uploads
        # the same bytes — delta packing would be pure overhead
        return None
    starts = np.asarray(_real_starts(run_offsets), dtype=np.int64)
    d = np.zeros(n, dtype=np.int64)
    d[1:] = col[1:].astype(np.int64) - col[:-1].astype(np.int64)
    d[starts] = 0  # run boundaries carry the base instead
    if d.min() < 0 or d.max() > 0xFFFF:
        return None  # not ascending / sparse keys: wide path wins
    m = pad_size(n)
    deltas = np.zeros(m, dtype=np.uint16)
    deltas[:n] = d.astype(np.uint16)
    r = len(starts)
    starts_p = _pad_starts(starts.tolist(), m)
    bases_p = np.zeros(len(starts_p), dtype=np.uint32)
    bases_p[:r] = col[starts]
    pad = np.zeros(m, dtype=np.uint8)
    pad[n:] = 1
    return deltas, starts_p, bases_p, pad, n, m, r


def _delta_reconstruct_lane(deltas, starts, bases, pad_flag):
    """In-kernel: rebuild the u32 key lane from the delta-packed upload
    (one cumsum + per-run rebase) — shared by both delta epilogues."""
    m = pad_flag.shape[0]
    iota = jnp.arange(m, dtype=jnp.int32)
    c = jnp.cumsum(deltas.astype(jnp.uint32), dtype=jnp.uint32)
    run = jnp.clip(
        jnp.searchsorted(starts, iota, side="right").astype(jnp.int32) - 1,
        0,
        starts.shape[0] - 1,
    )
    lane = bases[run] + (c - c[starts[run]])
    return jnp.where(pad_flag == 0, lane, jnp.uint32(0xFFFFFFFF))


@functools.lru_cache(maxsize=None)
def _dedup_select_delta_fn(backend: str = "xla"):
    """The dedup kernel for delta-packed single-lane keys: reconstruct the
    u32 lane on device (cumsum + per-run rebase), then the standard
    sort + keep-last epilogue with the compact-encoded download."""

    @jax.jit
    def f(deltas, starts, bases, pad_flag):
        lane = _delta_reconstruct_lane(deltas, starts, bases, pad_flag)
        pad_sorted, perm, _, keep_last, _ = sorted_segments(1, 0, [lane], [], pad_flag, engine=backend)
        sel = keep_last & (pad_sorted == 0)
        return pack_selection_compact(sel, perm, starts)

    return f


@functools.lru_cache(maxsize=None)
def _dedup_select_delta_wide_fn(backend: str = "xla"):
    """Delta-packed UPLOAD with the legacy index DOWNLOAD (pack_selected):
    keeps the halved uplink bytes when the compact download encoding is
    unavailable — run counts past its u8 run-id limit (>256)."""

    @jax.jit
    def f(deltas, starts, bases, pad_flag):
        lane = _delta_reconstruct_lane(deltas, starts, bases, pad_flag)
        pad_sorted, perm, _, keep_last, _ = sorted_segments(1, 0, [lane], [], pad_flag, engine=backend)
        sel = keep_last & (pad_sorted == 0)
        return pack_selected(sel, perm)

    return f


def deduplicate_select_delta_async(key_lanes: np.ndarray, run_offsets: Sequence[int], backend: str = "xla"):
    """Delta-packed dispatch for single-lane run-sorted keys; None when the
    lane does not qualify (multi-lane, non-ascending, sparse deltas, or a
    range the u16 narrowing already covers). Above 256 runs the upload
    stays delta-packed but the download falls back to packed indices
    (_dedup_select_delta_wide_fn). Both downloads route the sort+boundary
    preamble through the sort-engine seam."""
    if key_lanes.shape[1] != 1:
        return None
    packed = pack_delta_runs(key_lanes[:, 0], run_offsets)
    if packed is None:
        return None
    deltas, starts, bases, pad, n, m, num_runs = packed
    if backend == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 2)
    if num_runs > 256:
        return _dedup_select_delta_wide_fn(backend)(deltas, starts, bases, pad)
    outs = _dedup_select_delta_fn(backend)(deltas, starts, bases, pad)
    return ("compact", outs, n, num_runs, _runid_bits(len(starts)))


def _dedup_dispatch(key_lanes: np.ndarray, run_offsets: Sequence[int], backend: str):
    """One dispatch-policy site: delta-packed upload when it qualifies,
    compact (bit-packed) download when the run count allows, wide
    index-download otherwise. On the CPU backend every encoding is skipped
    (_link_encodings_pay_off): there are no link bytes to save. Callers
    (the tiled dispatcher) have already run the lane-compression seam, so
    every path here suppresses it (compress=False) — plans are made once
    per merge, not once per tile. The sort-engine seam (backend) composes
    with every encoding: the link format is independent of which kernel
    computes the sort + boundary."""
    if not _link_encodings_pay_off():
        return deduplicate_select_async(key_lanes, None, backend=backend, compress=False)
    handle = deduplicate_select_delta_async(key_lanes, run_offsets, backend=backend)
    if handle is not None:
        return handle
    handle = deduplicate_select_compact_async(key_lanes, run_offsets, compress=False, backend=backend)
    if handle is None:  # >256 runs: index-download fallback
        handle = deduplicate_select_async(key_lanes, None, backend=backend, compress=False)
    return handle


def deduplicate_resolve(handle) -> np.ndarray:
    if isinstance(handle, tuple) and handle[0] == "scalar":
        return handle[1]  # zero-width fast path: host-computed winner(s)
    if isinstance(handle, tuple) and handle[0] == "compact":
        _, (mask_bytes, runs_packed, count), n, num_runs, rbits = handle
        return unpack_selection_compact(mask_bytes, runs_packed, count, n, num_runs, rbits)
    packed, count = handle
    c = int(count)
    return np.asarray(packed[:c])


def deduplicate_select(
    key_lanes: np.ndarray, seq_lanes: np.ndarray | None = None, compress: bool | None = None
) -> np.ndarray:
    """Fused dedup: input lanes -> selected input-row indices (key order).
    Equivalent to deduplicate_take(merge_plan(...)) with ~3x less transfer."""
    return deduplicate_resolve(deduplicate_select_async(key_lanes, seq_lanes, compress=compress))


def deduplicate_select_tiled(
    key_lanes: np.ndarray,
    run_offsets: Sequence[int],
    tile_rows: int = 256 * 1024,
    backend: str = "xla",
    compress: bool | None = None,
) -> np.ndarray:
    """Key-range tiled dedup for runs concatenated in ascending-seq order
    (stability replaces seq lanes; see merge_plan docstring).

    The input is a concatenation of key-sorted runs (run r occupies rows
    [run_offsets[r], run_offsets[r+1])). Tiles cut the key space on the most
    significant lane — every duplicate of a key lands in exactly one tile —
    and each tile's kernel is dispatched asynchronously, so host<->device
    transfers of tile t+1 overlap the device sort of tile t. This is also the
    blockwise path for sections larger than device memory (the reference
    spills via MergeSorter :110-116; we tile by key range instead).
    Returns selected input-row indices in global key order."""
    return deduplicate_resolve_tiled(
        deduplicate_tiled_dispatch(key_lanes, run_offsets, tile_rows, backend, compress=compress)
    )


@functools.lru_cache(maxsize=None)
def _dedup_select_batched_fn(num_key_lanes: int):
    """vmapped sort + keep-last + pack over a (T, m) tile batch: every tile
    of a key-range tiled merge runs in ONE dispatch under ONE compile
    signature. This replaced the per-tile dispatch whose varying pad buckets
    and narrowing dtypes caused a fresh remote AOT compile per tile — the
    round-3 multi-tile collapse (104 K rows/s tiled vs 3.2 M single)."""

    @jax.jit
    def f(key_lanes, pad_flag):
        def per_tile(kl, pf):  # kl: tuple of (m,) uint lanes; pf: (m,) u8
            pad_sorted, perm, _, keep_last, _ = sorted_segments(
                num_key_lanes, 0, kl, [], pf
            )
            return pack_selected(keep_last & (pad_sorted == 0), perm)

        return jax.vmap(per_tile)(key_lanes, pad_flag)

    return f


# one batched tile dispatch stays under this many uint32-equivalent words
_TILE_BATCH_BUDGET_WORDS = 64 * 1024 * 1024


def _pow2_at_least(x: int) -> int:
    p = 1
    while p < x:
        p <<= 1
    return p


def _tile_boundaries(lane0_runs: list[np.ndarray], num_tiles: int) -> np.ndarray:
    """Approximate global quantiles of lane0 from per-run subsamples (each
    run is key-sorted): balanced tiles regardless of how rows distribute
    across runs. Unique boundaries keep every duplicate key in one tile."""
    total = sum(len(r) for r in lane0_runs)
    step = max(1, total // 65536)
    sample = np.sort(np.concatenate([r[::step] for r in lane0_runs]))
    cut_idx = np.linspace(0, len(sample) - 1, num_tiles + 1).astype(np.int64)[1:-1]
    return np.unique(sample[cut_idx])


def _gather_tiles(key_lanes, offsets, lane0_runs, boundaries):
    """Cut every run at the key boundaries and concatenate run slices per
    tile (run order preserved — stability carries the sequence tie-break).
    Returns [(tile_lanes (nt, k) u32, tile_global_rows (nt,) i32), ...] for
    the non-empty tiles, in ascending key-range order."""
    per_run_cuts = [np.searchsorted(lr, boundaries, side="left") for lr in lane0_runs]
    tiles = []
    for t in range(len(boundaries) + 1):
        slices, rows = [], []
        for r, lr in enumerate(lane0_runs):
            lo = 0 if t == 0 else int(per_run_cuts[r][t - 1])
            hi = len(lr) if t == len(boundaries) else int(per_run_cuts[r][t])
            if hi > lo:
                base = offsets[r]
                slices.append(key_lanes[base + lo : base + hi])
                rows.append(np.arange(base + lo, base + hi, dtype=np.int32))
        if slices:
            tiles.append(
                (
                    np.concatenate(slices) if len(slices) > 1 else slices[0],
                    np.concatenate(rows) if len(rows) > 1 else rows[0],
                )
            )
    return tiles


def deduplicate_tiled_dispatch(
    key_lanes: np.ndarray,
    run_offsets: Sequence[int],
    tile_rows: int = 256 * 1024,
    backend: str = "xla",
    compress: bool | None = None,
):
    """Async dispatch of the key-range tiled dedup; resolve with
    deduplicate_resolve_tiled.

    Uniform-batch design (VERDICT r4 #2): all tiles share one pad bucket
    m = pad_size(max tile rows), one narrowing dtype per lane (u16 iff every
    tile's range fits), and one chunk shape (T_chunk, m) — so the whole
    multi-tile merge compiles exactly ONE kernel, which the persistent
    compile cache then serves to every later merge at this tile size.
    Chunks are dispatched back-to-back without blocking; sections larger
    than the device budget stream through as equal-shaped chunks (the
    reference spills to disk instead: MergeSorter.java:110-116)."""
    key_lanes = np.ascontiguousarray(key_lanes)
    n = key_lanes.shape[0]
    offsets = list(run_offsets)
    if n == 0:
        return []
    from .lanes import compress_key_lanes, resolve_compress, scalar_dedup_winner

    # one compression plan for the whole merge; tiles inherit the packed
    # lanes (row order is untouched, so run offsets and the per-run key
    # ascent the tiler depends on both survive the transform)
    if resolve_compress(compress):
        key_lanes, _plan = compress_key_lanes(key_lanes, True)
    else:
        key_lanes = drop_constant_lanes(key_lanes)
    if key_lanes.shape[1] == 0:
        # all keys equal: one winner (no seq lanes on this path — run order
        # + stability carries the tie-break, so the winner is the last row)
        return [(("scalar", scalar_dedup_winner(None, n)), np.arange(n, dtype=np.int32))]
    if n <= tile_rows or len(offsets) < 3:
        return [(_dedup_dispatch(key_lanes, offsets, backend), np.arange(n, dtype=np.int32))]
    lane0_runs = [key_lanes[offsets[r] : offsets[r + 1], 0] for r in range(len(offsets) - 1)]
    num_tiles = max(2, (n + tile_rows - 1) // tile_rows)
    boundaries = _tile_boundaries(lane0_runs, num_tiles)
    tiles = _gather_tiles(key_lanes, offsets, lane0_runs, boundaries)
    if len(tiles) == 1 or backend == "pallas":
        # pallas epilogue is benchmarked per-tile; a single tile needs no batch
        handles = []
        for tile_lanes, tile_global in tiles:
            handles.append((_dedup_dispatch(tile_lanes, [0, len(tile_lanes)], backend), tile_global))
        return handles

    k = key_lanes.shape[1]
    m = pad_size(max(t[0].shape[0] for t in tiles))
    # uniform per-lane narrowing: u16 only when EVERY tile's range fits (one
    # dtype signature for the whole batch; per-tile min-shift keeps the win)
    mins = np.stack([t[0].min(axis=0) for t in tiles])  # (T, k)
    ptp_max = (np.stack([t[0].max(axis=0) for t in tiles]) - mins).max(axis=0)
    dtypes = [np.uint16 if int(p) < 0xFFFF else np.uint32 for p in ptp_max]

    words_per_tile = m * (len(dtypes) + 1)  # conservative: u16 lanes count full
    t_chunk = _pow2_at_least(len(tiles))
    max_chunk = max(1, _TILE_BATCH_BUDGET_WORDS // max(words_per_tile, 1))
    while t_chunk > max_chunk and t_chunk > 1:
        t_chunk >>= 1

    fn = _dedup_select_batched_fn(k)
    chunks = []
    for c0 in range(0, len(tiles), t_chunk):
        chunk = tiles[c0 : c0 + t_chunk]
        lanes_b = tuple(
            np.full((t_chunk, m), np.iinfo(d).max, dtype=d) for d in dtypes
        )
        pad_b = np.ones((t_chunk, m), dtype=np.uint8)
        for i, (tl, _) in enumerate(chunk):
            nt = tl.shape[0]
            for j in range(k):
                lanes_b[j][i, :nt] = (tl[:, j] - mins[c0 + i, j]).astype(dtypes[j])
            pad_b[i, :nt] = 0
        outs = fn(lanes_b, pad_b)  # async: next chunk assembles while this sorts
        chunks.append((outs, [rows for _, rows in chunk]))
    return ("batched", chunks)


def deduplicate_resolve_tiled(handles) -> np.ndarray:
    if isinstance(handles, tuple) and handles[0] == "batched":
        out = []
        for (packed, counts), rows_list in handles[1]:
            counts_np = np.asarray(counts)
            for t, rows in enumerate(rows_list):
                c = int(counts_np[t])
                if c:
                    out.append(rows[np.asarray(packed[t, :c])])
        return np.concatenate(out) if out else np.empty(0, dtype=np.int32)
    out = []
    for handle, rows in handles:
        local = deduplicate_resolve(handle)
        out.append(rows[local])
    return np.concatenate(out) if out else np.empty(0, dtype=np.int32)


def first_row_take(plan: MergePlan) -> np.ndarray:
    """First row per key (reference FirstRowMergeFunction.java)."""
    return plan.perm[plan.seg_start & plan.valid_sorted]


@functools.lru_cache(maxsize=None)
def _partial_update_fn():
    @jax.jit
    def f(perm, seg_id, field_valid, is_add, is_delete):
        # perm/seg_id: (m,) sorted coords; field_valid (F, m), is_add (m,),
        # is_delete (m,) in INPUT coords, padded with False
        m = perm.shape[0]
        pos = jnp.arange(m, dtype=jnp.int32)
        add_sorted = is_add[perm]
        del_sorted = is_delete[perm]
        # last delete position per segment (-1 if none)
        del_cand = jnp.where(del_sorted, pos, -1)
        last_del = jax.ops.segment_max(del_cand, seg_id, num_segments=m)
        gate = pos[None, :] > last_del[seg_id][None, :]
        fv_sorted = field_valid[:, perm]  # (F, m)
        last_per_field = segment_last_where(seg_id, fv_sorted & add_sorted[None, :] & gate, pos)
        src = jnp.where(last_per_field >= 0, perm[jnp.clip(last_per_field, 0, m - 1)], -1)
        # segment produces a row iff any add row after its last delete
        add_cand = jnp.where(add_sorted, pos, -1)
        last_add = jax.ops.segment_max(add_cand, seg_id, num_segments=m)
        exists = last_add > last_del
        return src, exists

    return f


def _ascending_block_starts(key_lanes: np.ndarray, max_blocks: int = 257) -> list[int] | None:
    """Host-side: split the input rows into maximal lexicographically
    non-decreasing blocks (block = run analog). Any input admits such a
    partition, so compact selection encodings work without plumbing run
    offsets: within a block, one winner per key means winners ascend with
    key. Returns None once more than max_blocks-1 boundaries are found
    (caller falls back to the index download)."""
    n, k = key_lanes.shape
    if n <= 1:
        return [0]
    a, b = key_lanes[:-1], key_lanes[1:]
    gt = np.zeros(n - 1, dtype=np.bool_)  # strict lex decrease at i -> i+1
    eq = np.ones(n - 1, dtype=np.bool_)
    for i in range(k):
        gt |= eq & (a[:, i] > b[:, i])
        eq &= a[:, i] == b[:, i]
    cuts = np.flatnonzero(gt)
    if len(cuts) + 1 >= max_blocks:
        return None
    return [0] + (cuts + 1).tolist()


def _partial_update_select(perm, pad_sorted, seg_id, field_valid, is_add, is_delete):
    """In-kernel shared core of BOTH fused partial-update kernels (compact
    and index-download): per-field last-valid-add-after-last-delete winner
    per segment, plus segment existence. Keeping it single-sourced means the
    two download encodings can never diverge semantically."""
    m = perm.shape[0]
    pos = jnp.arange(m, dtype=jnp.int32)
    add_sorted = is_add[perm]
    del_sorted = is_delete[perm]
    del_cand = jnp.where(del_sorted, pos, -1)
    last_del = jax.ops.segment_max(del_cand, seg_id, num_segments=m)
    gate = pos[None, :] > last_del[seg_id][None, :]
    fv_sorted = field_valid[:, perm]
    last_per_field = segment_last_where(seg_id, fv_sorted & add_sorted[None, :] & gate, pos)
    src = jnp.where(last_per_field >= 0, perm[jnp.clip(last_per_field, 0, m - 1)], -1)  # (F, m)
    add_cand = jnp.where(add_sorted, pos, -1)
    last_add = jax.ops.segment_max(add_cand, seg_id, num_segments=m)
    exists = last_add > last_del  # (m,) indexed by segment id
    return src, exists


@functools.lru_cache(maxsize=None)
def _fused_partial_update_compact_fn(num_key: int, num_seq: int, num_fields: int, engine: str = "xla"):
    """The fused partial-update kernel with compact downloads: instead of
    the (F, k) int32 source matrix (the dominant link bytes of the
    partial-update read on tunnel-attached chips), each field ships a
    bit-packed winner mask over input rows, presence bits per segment, and
    bit-packed block-ids of present winners; existence and keep-last ship
    as bits + block-ids too. ~10x fewer bytes; exact reconstruction in
    unpack_field_selection_compact."""

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag, field_valid, is_add, is_delete, starts):
        m = pad_flag.shape[0]
        pad_sorted, perm, _, keep_last, seg_id = sorted_segments(
            num_key, num_seq, key_lanes, seq_lanes, pad_flag, engine=engine
        )
        src, exists = _partial_update_select(perm, pad_sorted, seg_id, field_valid, is_add, is_delete)
        # ---- compact encodings --------------------------------------------
        rbits = _runid_bits(starts.shape[0])
        mask_last, runs_last, count = pack_selection_compact(
            keep_last & (pad_sorted == 0), perm, starts
        )
        exists_bits = jnp.packbits(exists)
        present = src >= 0  # (F, m) by segment id
        present_bits = jax.vmap(jnp.packbits)(present)
        src_cl = jnp.clip(src, 0, m - 1)
        win_mask = jnp.zeros((num_fields, m), jnp.bool_)
        win_mask = win_mask.at[jnp.arange(num_fields)[:, None], src_cl].max(present)
        win_bits = jax.vmap(jnp.packbits)(win_mask)
        blk = jnp.clip(
            jnp.searchsorted(starts, src_cl.reshape(-1), side="right").astype(jnp.int32) - 1,
            0,
            starts.shape[0] - 1,
        ).reshape(num_fields, m)

        def pack_front(pr, bi):
            _, packed = jax.lax.sort(
                [(~pr).astype(jnp.uint32), bi.astype(jnp.uint32)], num_keys=1, is_stable=True
            )
            return packed

        blk_front = jax.vmap(pack_front)(present, blk)  # (F, m) present blocks first
        blk_bits = _bitpack_rows(blk_front, rbits)  # (F, m*rbits//8)
        return win_bits, present_bits, blk_bits, exists_bits, mask_last, runs_last, count

    return f


def unpack_field_selection_compact(
    win_bits_f, present_bits_f, blk_bits_f, kk: int, n: int, rbits: int
) -> tuple[np.ndarray, np.ndarray]:
    """Host half for ONE field: -> (present mask (kk,), winner input indices
    for present segments, in segment order)."""
    present = np.unpackbits(np.asarray(present_bits_f[: (kk + 7) // 8]), count=kk).astype(bool)
    c = int(present.sum())
    if c == 0:
        return present, np.empty(0, dtype=np.int32)
    winners = np.flatnonzero(
        np.unpackbits(np.asarray(win_bits_f[: (n + 7) // 8]), count=n)
    ).astype(np.int32)
    vals = _interleave_winners(winners, _unpack_runids(blk_bits_f, c, rbits))
    return present, vals


@functools.lru_cache(maxsize=None)
def _fused_partial_update_fn(num_key: int, num_seq: int, num_fields: int, engine: str = "xla"):
    """Sort + segment + partial-update selection in ONE kernel: the plan never
    leaves the device, and the only downloads are the per-field source indices
    (F, k), the per-key existence bits and the winning-row indices — instead
    of 4 full plan arrays + per-field round trips. This is the fusion the
    dedup engine got in round 1 (_dedup_select_fn), applied to partial-update."""

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag, field_valid, is_add, is_delete):
        pad_sorted, perm, _, keep_last, seg_id = sorted_segments(
            num_key, num_seq, key_lanes, seq_lanes, pad_flag, engine=engine
        )
        src, exists = _partial_update_select(perm, pad_sorted, seg_id, field_valid, is_add, is_delete)
        packed, count = pack_selected(keep_last & (pad_sorted == 0), perm)
        return src, exists, packed, count

    return f


def fused_partial_update(
    key_lanes: np.ndarray,  # (n, K) uint32
    seq_lanes: np.ndarray | None,  # (n, S) uint32
    field_valid: np.ndarray,  # (F, n) bool
    row_kind: np.ndarray,  # (n,) uint8
    remove_record_on_delete: bool = False,
    compress: bool | None = None,
    engine: str = "xla",
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Single-call partial-update merge: returns (src (F, k), exists (k,),
    last_take (k,)) in key order — the same contract as
    merge_plan + partial_update_takes + keep-last takes, one device trip.
    When the input decomposes into <=256 ascending-key blocks (always true
    for real sections), downloads use the compact bit-packed encoding.
    Key lanes run through the compression seam (truncate + pack); an
    all-constant key sorts on sequence lanes alone (k=0 kernel)."""
    from ..types import RowKind

    klp, slp, pad, n, k, s, m, _plan = prepare_lanes_planned(key_lanes, seq_lanes, compress=compress)
    is_add = np.isin(row_kind, (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER)))
    if remove_record_on_delete:
        is_delete = row_kind == int(RowKind.DELETE)
    else:
        is_delete = np.zeros_like(is_add)
    F = field_valid.shape[0]
    fv = np.zeros((max(F, 1), m), dtype=np.bool_)
    if F:
        fv[:F, :n] = field_valid
    if engine == "pallas":
        from .pallas_kernels import note_dispatch

        note_dispatch(m, 1 + k + s)
    starts_real = _ascending_block_starts(key_lanes) if F and _link_encodings_pay_off() else None
    if starts_real is not None:
        starts_p = _pad_starts(starts_real, m)
        rbits = _runid_bits(len(starts_p))
        win_bits, present_bits, blk_bits, exists_bits, mask_last, runs_last, count = (
            _fused_partial_update_compact_fn(k, s, fv.shape[0], engine)(
                klp, slp, pad, fv, pad_to(is_add, m, False), pad_to(is_delete, m, False), starts_p
            )
        )
        kk = int(count)
        last_take = unpack_selection_compact(
            mask_last, runs_last, count, n, len(starts_real), rbits
        )
        exists = np.unpackbits(np.asarray(exists_bits[: (kk + 7) // 8]), count=kk).astype(bool)
        # one download per tensor (not per field): 3 link round-trips total
        per = 8 // rbits
        winb = np.asarray(win_bits[:, : (n + 7) // 8])
        prb = np.asarray(present_bits[:, : (kk + 7) // 8])
        blb = np.asarray(blk_bits[:, : max(1, (kk + per - 1) // per)])
        src_out = np.full((F, kk), -1, dtype=np.int32)
        for f in range(F):
            present, vals = unpack_field_selection_compact(winb[f], prb[f], blb[f], kk, n, rbits)
            src_out[f, present] = vals
        return src_out, exists, last_take
    src, exists, packed, count = _fused_partial_update_fn(k, s, fv.shape[0], engine)(
        klp, slp, pad, fv, pad_to(is_add, m, False), pad_to(is_delete, m, False)
    )
    kk = int(count)
    # device-side slicing: only (F, k) + 2k elements cross the link
    return (
        np.asarray(src[:F, :kk]),
        np.asarray(exists[:kk]),
        np.asarray(packed[:kk]),
    )


def partial_update_takes(
    plan: MergePlan,
    field_valid: np.ndarray,  # (F, n) bool — per merged field, non-null mask (input coords)
    row_kind: np.ndarray,  # (n,) uint8 (input coords)
    remove_record_on_delete: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Partial-update merge engine (reference PartialUpdateMergeFunction.java:57):
    per field, the output value is the field's latest non-null value in
    (key, seq) order. Returns (src, exists) sliced to the valid segments:
    src (F, num_segments) input-row index per field (-1 => null), exists
    (num_segments,) bool — False when remove-record-on-delete dropped the row.
    """
    m = plan.m
    is_add = np.isin(row_kind, (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER)))
    if remove_record_on_delete:
        is_delete = row_kind == int(RowKind.DELETE)
    else:
        is_delete = np.zeros_like(is_add)
    src, exists = _partial_update_fn()(
        jnp.asarray(plan.perm),
        jnp.asarray(plan.seg_id),
        jnp.asarray(pad_to(field_valid.T, m, False).T if field_valid.shape[1] != m else field_valid),
        jnp.asarray(pad_to(is_add, m, False)),
        jnp.asarray(pad_to(is_delete, m, False)),
    )
    k = plan.num_segments
    return np.asarray(src)[:, :k], np.asarray(exists)[:k]
