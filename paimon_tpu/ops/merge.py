"""Sort + segment + select: the device merge kernel.

Replaces the reference's SortMergeReader heap loop and MergeFunction
application (/root/reference/paimon-core/.../mergetree/compact/
SortMergeReaderWithMinHeap.java:54-70 orders by (userKey, udsSeq, seqNumber);
:167-177 feeds same-key groups to the merge function). Here the ordering is
one stable lexicographic `lax.sort` and the per-key group logic is masks and
segment reductions — no data-dependent control flow, fully XLA-fusable.

Coordinate systems: "input" = row index into the concatenated runs;
"sorted" = position after the sort. `perm` maps sorted -> input.

Shapes: every device array is padded to a power-of-two bucket `m` so XLA
compiles once per (lane arity, size bucket). Pad rows carry a set pad flag
(the most significant sort lane), so valid rows occupy sorted slots [0, n)
and pad rows segment separately. The only dynamic-shape step — boolean
keep-mask -> index compaction — happens host-side in numpy where it's free.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..types import RowKind

__all__ = [
    "MergePlan",
    "merge_plan",
    "pad_size",
    "deduplicate_take",
    "first_row_take",
    "partial_update_takes",
]

_MIN_PAD = 128


def pad_size(n: int) -> int:
    """Next power of two (>=128): bounds the jit cache to O(log n) entries."""
    p = _MIN_PAD
    while p < n:
        p <<= 1
    return p


def pad_to(arr: np.ndarray, m: int, fill=0) -> np.ndarray:
    out = np.full((m,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


@functools.lru_cache(maxsize=None)
def _plan_fn(num_key_lanes: int, num_seq_lanes: int):
    """Builds the jitted sort+segment kernel for a lane arity."""

    @jax.jit
    def f(key_lanes, seq_lanes, pad_flag):
        # key_lanes: (K, m) uint32; seq_lanes: (S, m) uint32; pad_flag: (m,) uint32
        m = pad_flag.shape[0]
        iota = jnp.arange(m, dtype=jnp.int32)
        operands = (
            [pad_flag]
            + [key_lanes[i] for i in range(num_key_lanes)]
            + [seq_lanes[i] for i in range(num_seq_lanes)]
            + [iota]
        )
        out = jax.lax.sort(operands, num_keys=1 + num_key_lanes + num_seq_lanes, is_stable=True)
        perm = out[-1]
        # segment detection over (pad, key lanes) only — sequence lanes do NOT
        # split segments (same key, different seq = one merge group)
        seg_keys = jnp.stack(out[: 1 + num_key_lanes], axis=0)
        neq = jnp.any(seg_keys[:, 1:] != seg_keys[:, :-1], axis=0)
        seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), neq])
        keep_last = jnp.concatenate([neq, jnp.ones((1,), jnp.bool_)])
        seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
        return perm, seg_start, keep_last, seg_id

    return f


@dataclass
class MergePlan:
    """Sorted view of the concatenated inputs of one merge. Arrays have
    padded length m; valid rows occupy sorted slots [0, n)."""

    perm: np.ndarray  # (m,) sorted -> input row index (int32)
    seg_start: np.ndarray  # (m,) bool, sorted coords
    keep_last: np.ndarray  # (m,) bool, sorted coords (last row of segment)
    seg_id: np.ndarray  # (m,) int32, sorted coords
    n: int  # valid rows
    m: int  # padded size

    @property
    def valid_sorted(self) -> np.ndarray:
        return np.arange(self.m) < self.n

    @property
    def num_segments(self) -> int:
        """Segments holding valid rows (pad segments sort after them)."""
        return int(self.seg_id[self.n - 1]) + 1 if self.n else 0


def merge_plan(key_lanes: np.ndarray, seq_lanes: np.ndarray | None = None) -> MergePlan:
    """key_lanes: (n, K) uint32. seq_lanes: (n, S) uint32 ordering within a
    key group (user-defined sequence lanes first, then sequence-number lanes —
    the reference's (udsSeq, seqNumber) tie-break). Stable: remaining ties
    resolve to input order, which is run order — same as the heap's reader
    index tie-break."""
    n, k = key_lanes.shape
    if seq_lanes is None:
        seq_lanes = np.zeros((n, 0), dtype=np.uint32)
    s = seq_lanes.shape[1]
    m = pad_size(n)
    kl = np.full((k, m), 0xFFFFFFFF, dtype=np.uint32)
    kl[:, :n] = key_lanes.T
    sl = np.zeros((s, m), dtype=np.uint32)
    sl[:, :n] = seq_lanes.T
    pad = np.zeros(m, dtype=np.uint32)
    pad[n:] = 1
    perm, seg_start, keep_last, seg_id = _plan_fn(k, s)(kl, sl, pad)
    return MergePlan(
        perm=np.asarray(perm),
        seg_start=np.asarray(seg_start),
        keep_last=np.asarray(keep_last),
        seg_id=np.asarray(seg_id),
        n=n,
        m=m,
    )


def deduplicate_take(plan: MergePlan) -> np.ndarray:
    """Input-row indices of each key's last (key, seq) row — the deduplicate
    merge engine (reference DeduplicateMergeFunction.java:31: last row wins).
    Output is in key order."""
    return plan.perm[plan.keep_last & plan.valid_sorted]


def first_row_take(plan: MergePlan) -> np.ndarray:
    """First row per key (reference FirstRowMergeFunction.java)."""
    return plan.perm[plan.seg_start & plan.valid_sorted]


@functools.lru_cache(maxsize=None)
def _partial_update_fn():
    @jax.jit
    def f(perm, seg_id, field_valid, is_add, is_delete):
        # perm/seg_id: (m,) sorted coords; field_valid (F, m), is_add (m,),
        # is_delete (m,) in INPUT coords, padded with False
        m = perm.shape[0]
        pos = jnp.arange(m, dtype=jnp.int32)
        add_sorted = is_add[perm]
        del_sorted = is_delete[perm]
        # last delete position per segment (-1 if none)
        del_cand = jnp.where(del_sorted, pos, -1)
        last_del = jax.ops.segment_max(del_cand, seg_id, num_segments=m)
        gate = pos[None, :] > last_del[seg_id][None, :]
        fv_sorted = field_valid[:, perm]  # (F, m)
        cand = jnp.where(fv_sorted & add_sorted[None, :] & gate, pos[None, :], -1)
        last_per_field = jax.vmap(lambda c: jax.ops.segment_max(c, seg_id, num_segments=m))(cand)
        src = jnp.where(last_per_field >= 0, perm[jnp.clip(last_per_field, 0, m - 1)], -1)
        # segment produces a row iff any add row after its last delete
        add_cand = jnp.where(add_sorted, pos, -1)
        last_add = jax.ops.segment_max(add_cand, seg_id, num_segments=m)
        exists = last_add > last_del
        return src, exists

    return f


def partial_update_takes(
    plan: MergePlan,
    field_valid: np.ndarray,  # (F, n) bool — per merged field, non-null mask (input coords)
    row_kind: np.ndarray,  # (n,) uint8 (input coords)
    remove_record_on_delete: bool = False,
) -> tuple[np.ndarray, np.ndarray]:
    """Partial-update merge engine (reference PartialUpdateMergeFunction.java:57):
    per field, the output value is the field's latest non-null value in
    (key, seq) order. Returns (src, exists) sliced to the valid segments:
    src (F, num_segments) input-row index per field (-1 => null), exists
    (num_segments,) bool — False when remove-record-on-delete dropped the row.
    """
    m = plan.m
    is_add = np.isin(row_kind, (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER)))
    if remove_record_on_delete:
        is_delete = row_kind == int(RowKind.DELETE)
    else:
        is_delete = np.zeros_like(is_add)
    src, exists = _partial_update_fn()(
        jnp.asarray(plan.perm),
        jnp.asarray(plan.seg_id),
        jnp.asarray(pad_to(field_valid.T, m, False).T if field_valid.shape[1] != m else field_valid),
        jnp.asarray(pad_to(is_add, m, False)),
        jnp.asarray(pad_to(is_delete, m, False)),
    )
    k = plan.num_segments
    return np.asarray(src)[:, :k], np.asarray(exists)[:k]
