"""Dictionary-domain unification: codes as the merge currency (ISSUE 10).

LSM-OPD computes directly on compressed LSM data; LUDA's GPU compactor
re-maps input dictionaries on device instead of decompressing. This module
is that move for the uint32-lane merge kernel: when every input of a merge
is dictionary-encoded, the per-file sorted pools unify into ONE pool and
each input's codes re-map through a vectorized gather — the re-mapped codes
are directly comparable (rank order == string order), so they become key
lanes, dedup/aggregation operands, and finally the dictionary page of the
output file without a string object ever materializing in between.

The pieces:

  sort_dictionary — one file dictionary (parquet insertion order) → sorted
                    pool + old-code→rank gather table
  unify_pools     — N sorted pools → one sorted pool + per-input gather
                    tables (the LUDA re-map; host object work is O(sum of
                    POOL sizes), never O(rows))
  remap_codes     — the |rows|-sized gather, numpy engine with a jittable
                    JAX twin (PAIMON_TPU_DICT_ENGINE=jax)
  unify_columns   — Column.concat's seam: concatenate code-backed columns
                    entirely in the code domain
  prune_pool      — drop pool entries no surviving code references before a
                    dictionary page is written (file dictionaries stay
                    minimal across compaction chains)
  partition_rows  — value-hash shuffle partitioner (ISSUE 20): rows hash
                    over pool VALUES gathered through their codes, so two
                    workers with disjoint code spaces agree on the shuffle
                    range of every shared group key

`merge.dict-domain` (default off) gates the reader that produces code-backed
columns; PAIMON_TPU_DICT_DOMAIN overrides in either direction (the
decoder/encoder/lanes rollout pattern). A unified domain larger than
`merge.dict-domain.pool-limit` falls back to the expanded path per merge —
codes stay uint32 and the pool stays cheap to unify.
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import numpy as np

__all__ = [
    "resolve_dict_domain",
    "resolve_pool_limit",
    "sort_dictionary",
    "unify_pools",
    "remap_codes",
    "remap_codes_np",
    "remap_codes_jax",
    "unify_columns",
    "prune_pool",
    "cache_usable",
    "encode_column",
    "pool_value_hashes",
    "partition_rows",
    "partition_rows_np",
    "partition_rows_jax",
]

DEFAULT_POOL_LIMIT = 1 << 20  # codes stay far inside uint32/int32 range


def resolve_dict_domain(enabled: bool | str | None) -> bool:
    """One resolution order everywhere: the PAIMON_TPU_DICT_DOMAIN env var
    (verify stages force both paths) beats the caller's option value, which
    beats the default (off)."""
    env = os.environ.get("PAIMON_TPU_DICT_DOMAIN", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if enabled is None:
        return False
    if isinstance(enabled, str):
        return enabled.strip().lower() in ("1", "on", "true")
    return bool(enabled)


def resolve_pool_limit(limit: int | str | None) -> int:
    """PAIMON_TPU_DICT_POOL_LIMIT env beats the option value beats the
    default. The limit bounds BOTH a single file's dictionary (reader
    admission) and a unified merge domain (concat fallback)."""
    env = os.environ.get("PAIMON_TPU_DICT_POOL_LIMIT", "").strip()
    if env:
        return int(env)
    if limit is None:
        return DEFAULT_POOL_LIMIT
    return int(limit)


def _metrics():
    from ..metrics import dict_metrics

    return dict_metrics()


def sort_dictionary(dictionary: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(sorted pool, remap) for one file dictionary: pool is the sorted
    distinct value set and remap[old_code] is the value's rank in the pool.
    Parquet dictionaries are insertion-ordered and normally duplicate-free;
    np.unique tolerates duplicates (they collapse to one rank).

    String/bytes dictionaries normalize to object pools; FIXED-WIDTH
    dictionaries (int32/int64/date — ISSUE 12) keep their native dtype, so
    code-backed numeric columns expand to exactly the array the plain
    decode would have produced."""
    if len(dictionary) == 0:
        return dictionary, np.zeros(0, dtype=np.uint32)
    pool, inverse = np.unique(dictionary, return_inverse=True)
    if pool.dtype != np.dtype(object) and pool.dtype.kind not in "biufM":
        pool = pool.astype(object)
    return pool, inverse.astype(np.uint32, copy=False)


def unify_pools(
    pools: Sequence[np.ndarray],
) -> tuple[np.ndarray, list[np.ndarray | None]]:
    """Merge N sorted pools into one sorted pool; returns per-input gather
    tables mapping input ranks to unified ranks (None = identity). Object
    comparisons stay O(sum |pool|) — the rows never participate."""
    g = _metrics()
    t0 = time.perf_counter()
    first = pools[0]
    same = all(p is first for p in pools)
    if not same and all(len(p) == len(first) for p in pools):
        # equal-content pools (a fact key spanning the whole dimension, a
        # re-read of the same file set): one vectorized compare beats the
        # full unify by an order of magnitude
        try:
            same = all(bool(np.asarray(p == first).all()) for p in pools[1:])
        except (TypeError, ValueError):
            same = False
    if same:
        g.counter("pools_unified").inc(len(pools))
        g.histogram("unify_ms").update((time.perf_counter() - t0) * 1000)
        return first, [None] * len(pools)
    merged = np.concatenate([p for p in pools]) if pools else np.empty(0, dtype=object)
    if len(merged) == 0:
        unified = merged
        remaps: list[np.ndarray | None] = [np.zeros(0, dtype=np.uint32) for _ in pools]
    elif merged.dtype == np.dtype(object) and len(merged) >= 65_536:
        # large object domains: dedupe + rank through arrow's C hash table
        # (the build_string_pool move) — np.unique would object-compare-sort
        # the whole concatenation, which dominates big code-domain joins
        got = _unify_pools_arrow(pools)
        if got is None:
            unified, inverse = np.unique(merged, return_inverse=True)
            remaps = _split_inverse(inverse, pools)
        else:
            unified, remaps = got
    else:
        unified, inverse = np.unique(merged, return_inverse=True)
        # object pools stay object; fixed-width pools keep their native
        # dtype (the expansion contract of sort_dictionary)
        if unified.dtype != np.dtype(object) and merged.dtype == np.dtype(object):
            unified = unified.astype(object)
        remaps = _split_inverse(inverse, pools)
    g.counter("pools_unified").inc(len(pools))
    g.histogram("unify_ms").update((time.perf_counter() - t0) * 1000)
    return unified, remaps


def _split_inverse(inverse: np.ndarray, pools) -> list:
    inverse = inverse.astype(np.uint32, copy=False)
    remaps = []
    off = 0
    for p in pools:
        remaps.append(inverse[off : off + len(p)])
        off += len(p)
    return remaps


def _unify_pools_arrow(pools):
    """(unified sorted pool, per-input remaps) through arrow's C hash
    table: unique over all pools, one object sort of the DISTINCT set only,
    then index_in per input pool — identical output contract to the
    np.unique path, at hash speed. None = values arrow cannot hash."""
    try:
        import pyarrow as pa
        import pyarrow.compute as pc

        arrays = [pa.array(p, from_pandas=True) for p in pools]
        chunked = pa.chunked_array([a for a in arrays if len(a)])
        uniq = pc.drop_null(pc.unique(chunked)).to_numpy(zero_copy_only=False)
        if uniq.dtype != np.dtype(object):
            uniq = uniq.astype(object)
        uniq.sort()
        value_set = pa.array(uniq, from_pandas=True)
        remaps = [
            pc.index_in(a, value_set=value_set)
            .to_numpy(zero_copy_only=False)
            .astype(np.uint32)
            for a in arrays
        ]
        return uniq, remaps
    except (TypeError, ValueError, OverflowError, pa.lib.ArrowInvalid):
        return None


def remap_codes_np(remap: np.ndarray, codes: np.ndarray) -> np.ndarray:
    return remap.take(codes).astype(np.uint32, copy=False)


def remap_codes_jax(remap, codes):
    import jax.numpy as jnp

    return jnp.take(jnp.asarray(remap), jnp.asarray(codes), axis=0)


def remap_codes(remap: np.ndarray | None, codes: np.ndarray) -> np.ndarray:
    """codes → remap[codes], the |rows|-sized vectorized gather (LUDA's
    device re-map). Engine-routed like decode.kernels.gather: numpy by
    default, the JAX twin under PAIMON_TPU_DICT_ENGINE=jax."""
    codes = codes.astype(np.uint32, copy=False)
    if remap is None or len(codes) == 0:
        return codes
    _metrics().counter("codes_remapped").inc(len(codes))
    if os.environ.get("PAIMON_TPU_DICT_ENGINE") == "jax":
        return np.asarray(remap_codes_jax(remap, codes)).astype(np.uint32, copy=False)
    return remap_codes_np(remap, codes)


def cache_usable(col) -> bool:
    """True when a Column's dict_cache is a full-length (pool, codes) pair —
    the precondition every code-domain consumer checks."""
    cache = getattr(col, "dict_cache", None)
    return cache is not None and len(cache[1]) == len(col)


def unify_columns(cols: Sequence, validity: np.ndarray | None, limit: int | None = None):
    """Concatenate code-backed columns without leaving the code domain:
    unify their pools, re-map and concatenate their codes. Returns the
    concatenated code-backed Column, or None when the unified domain
    exceeds the pool limit (the caller falls back to expanded concat)."""
    from ..data.batch import Column

    pools = [c.dict_cache[0] for c in cols]
    if sum(len(p) for p in pools) > resolve_pool_limit(limit) and len(set(map(id, pools))) > 1:
        # cheap upper bound first; the exact unified size needs the unify
        # itself, which we refuse to pay past the limit
        g = _metrics()
        g.counter("fallback_expanded").inc(sum(len(c) for c in cols))
        return None
    unified, remaps = unify_pools(pools)
    if len(unified) > resolve_pool_limit(limit):
        g = _metrics()
        g.counter("fallback_expanded").inc(sum(len(c) for c in cols))
        return None
    codes = np.concatenate(
        [remap_codes(r, c.dict_cache[1]) for r, c in zip(remaps, cols)]
    )
    return Column.from_codes(unified, codes, validity)


def encode_column(col) -> tuple[np.ndarray, np.ndarray]:
    """One Column → (sorted pool, uint32 codes) with NULL rows encoded as the
    sentinel code ``len(pool)`` — the GROUP-BY key currency (ISSUE 16).

    Code-backed columns stay in the compressed domain: their cached pool is
    pruned to the referenced entries and the cached codes re-rank without a
    value ever materializing. Expanded columns encode via np.unique over the
    valid subset (fixed-width pools keep their native dtype, strings
    normalize to object); a mixed-type object column that numpy cannot sort
    falls back to a first-seen dict walk — the pool may then be unsorted,
    which is fine for grouping (equality is all that matters) and unify_pools
    re-sorts the concatenation anyway."""
    n = len(col)
    valid = col.valid_mask()
    if cache_usable(col):
        pool, codes = col.dict_cache
        pool, codes = prune_pool(pool, codes, None if valid.all() else valid)
        codes = codes.astype(np.uint32, copy=True)
        codes[~valid] = len(pool)
        return pool, codes
    values = col.values
    live = values[valid]
    codes = np.empty(n, dtype=np.uint32)
    try:
        pool, inv = np.unique(live, return_inverse=True)
        if pool.dtype != np.dtype(object) and values.dtype == np.dtype(object):
            pool = pool.astype(object)
    except TypeError:
        seen: dict = {}
        inv = np.empty(len(live), dtype=np.uint32)
        for i, v in enumerate(live):
            inv[i] = seen.setdefault(v, len(seen))
        pool = np.empty(len(seen), dtype=object)
        for v, c in seen.items():
            pool[c] = v
    codes[valid] = inv.astype(np.uint32, copy=False)
    codes[~valid] = len(pool)
    return pool, codes


def prune_pool(
    pool: np.ndarray, codes: np.ndarray, validity: np.ndarray | None = None
) -> tuple[np.ndarray, np.ndarray]:
    """Restrict a (pool, codes) pair to the entries actually referenced by
    valid rows: returns (pruned pool, re-mapped codes). The pruned pool is
    exactly the sorted distinct set of the column's present values — the
    same pool build_string_pool computes from expanded values — so lane
    ranks and emitted dictionary pages are identical in both domains.
    Codes at invalid slots are re-mapped through a clip (their value is
    meaningless by contract)."""
    if len(pool) == 0:
        return pool, codes.astype(np.uint32, copy=False)
    live = codes if validity is None else codes[validity]
    used = np.zeros(len(pool), dtype=np.bool_)
    used[live] = True
    if used.all():
        return pool, codes.astype(np.uint32, copy=False)
    remap = np.cumsum(used, dtype=np.int64) - 1
    remap[~used] = 0  # dead entries: clip to a harmless rank
    return pool[used], remap_codes(remap.astype(np.uint32), codes)


# ---------------------------------------------------------------------------
# value-hash shuffle partitioner (ISSUE 20): the distributed-aggregation
# exchange keys. Hashes are pure functions of VALUES — never of pool ranks,
# process ids, or PYTHONHASHSEED — so every worker routes a given group key
# to the same shuffle range despite per-worker code spaces. Cost discipline:
# one hash per POOL entry (O(|pool|) host work), then an O(rows) uint32
# gather + mix, numpy engine with a bit-identical JAX twin.
# ---------------------------------------------------------------------------
_NULL_HASH = 0x9E3779B9  # the NULL sentinel's fixed hash slot
_HASH_SEED = 2166136261  # FNV-1a offset basis
_HASH_PRIME = 16777619  # FNV-1a prime (column mixing step)


def _fmix32(xp, h):
    """murmur3's 32-bit finalizer — pure uint32 shifts/multiplies, so the
    numpy and jax twins are bit-identical by construction."""
    h = h ^ (h >> 16)
    h = h * xp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * xp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def pool_value_hashes(pool: np.ndarray) -> np.ndarray:
    """One deterministic uint32 hash per pool entry, plus a trailing slot
    for the NULL sentinel code ``len(pool)``. Object entries hash their
    utf-8 bytes (crc32 — stable across processes); fixed-width entries hash
    canonicalized 64-bit views (-0.0 folds into +0.0 and NaNs collapse to
    one pattern, mirroring np.unique's equality so unify_pools and the
    partitioner never disagree about which values are the same group)."""
    import zlib

    n = len(pool)
    out = np.empty(n + 1, dtype=np.uint32)
    out[n] = np.uint32(_NULL_HASH)
    if n == 0:
        return out
    if pool.dtype == np.dtype(object):
        for i, v in enumerate(pool):
            if isinstance(v, str):
                b = v.encode("utf-8")
            elif isinstance(v, (bytes, bytearray)):
                b = bytes(v)
            else:
                b = repr(v).encode("utf-8")
            out[i] = zlib.crc32(b) & 0xFFFFFFFF
        return out
    kind = pool.dtype.kind
    if kind == "f":
        x = pool.astype(np.float64, copy=True)
        x += 0.0  # -0.0 + 0.0 == +0.0: signed zeros hash together
        bits = x.view(np.uint64).copy()
        bits[np.isnan(x)] = np.uint64(0x7FF8000000000000)  # one NaN pattern
    elif kind in "Mm":
        bits = pool.view(np.int64).astype(np.uint64)
    elif kind == "u":
        bits = pool.astype(np.uint64)
    else:  # signed ints / bools: two's-complement 64-bit view
        bits = pool.astype(np.int64).view(np.uint64)
    lo = (bits & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    hi = (bits >> np.uint64(32)).astype(np.uint32)
    out[:n] = _fmix32(np, lo ^ _fmix32(np, hi))
    return out


def partition_rows_np(tables: Sequence[np.ndarray], codes_list, num_parts: int) -> np.ndarray:
    h = np.full(len(codes_list[0]), _HASH_SEED, dtype=np.uint32)
    for tbl, codes in zip(tables, codes_list):
        h = _fmix32(np, (h ^ tbl.take(codes.astype(np.int64, copy=False))) * np.uint32(_HASH_PRIME))
    return (h % np.uint32(num_parts)).astype(np.uint32)


def partition_rows_jax(tables, codes_list, num_parts: int):
    import jax.numpy as jnp

    h = jnp.full(len(codes_list[0]), _HASH_SEED, dtype=jnp.uint32)
    for tbl, codes in zip(tables, codes_list):
        gathered = jnp.take(jnp.asarray(tbl), jnp.asarray(codes.astype(np.int64, copy=False)), axis=0)
        h = _fmix32(jnp, (h ^ gathered) * jnp.uint32(_HASH_PRIME))
    return h % jnp.uint32(num_parts)


def partition_rows(pools: Sequence[np.ndarray], codes_list, num_parts: int) -> np.ndarray:
    """(n,) uint32 shuffle-range id per row: per-column value hashes
    (pool_value_hashes, NULL sentinel included) gather through the uint32
    codes and mix across key columns. Engine-routed like remap_codes —
    numpy by default, the JAX twin under PAIMON_TPU_DICT_ENGINE=jax; both
    are bit-identical (pure uint32 integer mixing). Collisions only skew
    range balance, never correctness: a value maps to exactly one range."""
    if not codes_list:
        return np.zeros(0, np.uint32)
    if num_parts <= 1:
        return np.zeros(len(codes_list[0]), np.uint32)
    tables = [pool_value_hashes(p) for p in pools]
    if os.environ.get("PAIMON_TPU_DICT_ENGINE") == "jax":
        return np.asarray(partition_rows_jax(tables, codes_list, num_parts)).astype(
            np.uint32, copy=False
        )
    return partition_rows_np(tables, codes_list, num_parts)
