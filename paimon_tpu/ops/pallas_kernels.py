"""Pallas TPU kernels for the merge hot path.

Two tiers, selected by table option `sort-engine=pallas`
(CoreOptions.SortEngine); `interpret=True` runs the same kernels on CPU so
CI proves bit-identical output without hardware:

1. **Fused sort+segment kernel** (`fused_sort_segments`): the whole inner
   merge — stable lexicographic sort, run-boundary detection, and the
   keep-last winner mask — in ONE `pallas_call` over VMEM-resident lanes.
   The sort is a bitonic compare-exchange network over the stacked
   (pad, key lanes, seq lanes, iota) matrix: the iota lane rides as the
   final comparison lane, which makes the strict total order identical to
   XLA's stable variadic sort, so the permutation AND the segmentation are
   bit-for-bit the `lax.sort` path's. Unsigned lanes are bijected into
   sign-flipped int32 space (order-preserving) because Mosaic's integer
   compares are signed. Boundary detection then folds XORs across the
   segment lanes of adjacent sorted rows — all while the data never leaves
   VMEM.

2. **Boundary-sweep kernel** (`keep_last_mask`): the post-`lax.sort`
   fallback when the fused kernel does not qualify (`fusable`): a
   bandwidth-bound elementwise pass detecting segment boundaries across all
   key lanes at once, each grid step loading a block of the stacked lanes
   plus a one-element lookahead.

The fallback ladder mirrors every other engine in this repo: numpy oracle
(sort-engine=numpy) == xla-segmented == pallas, asserted per-seed by
tests/test_pallas_merge.py; when pallas itself is unavailable (import
failure, oversized batch) the dispatch silently degrades to the
`lax.sort` path and counts `pallas{fallback_xla}`.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:  # the pallas import can fail on exotic jax builds: degrade, don't die
    from jax.experimental import pallas as pl

    _PALLAS_OK = True
except Exception:  # pragma: no cover - import-time environment dependent
    pl = None
    _PALLAS_OK = False

__all__ = [
    "keep_last_mask",
    "fused_sort_segments",
    "fusable",
    "note_dispatch",
    "pallas_interpret",
]

_BLOCK = 2048

# fused-kernel admission: rows beyond this take the lax.sort + sweep path
# (VMEM is ~16 MB/core; the compare network holds (lanes+1) int32 rows plus
# double-buffered temps). Both knobs are env-tunable for chip experiments.
_FUSE_MAX_ROWS = int(os.environ.get("PAIMON_TPU_PALLAS_FUSE_ROWS", str(1 << 18)))
_FUSE_MAX_LANES = int(os.environ.get("PAIMON_TPU_PALLAS_FUSE_LANES", "8"))
_FUSE_VMEM_BUDGET = 12 * 1024 * 1024


def pallas_interpret() -> bool:
    """interpret=True whenever the live backend is CPU: the same kernel
    trace serves CI (interpreted) and the chip (Mosaic-compiled)."""
    return jax.default_backend() == "cpu"


def fusable(m: int, num_lanes: int) -> bool:
    """Static admission test for the fused sort+segment kernel: m must be a
    power of two (pad_size guarantees it) small enough that the compare
    network and its temps stay VMEM-resident, with a bounded lane count
    (each extra lane widens every compare-exchange)."""
    if not _PALLAS_OK:
        return False
    if m < 2 or m & (m - 1):
        return False
    if m > _FUSE_MAX_ROWS or num_lanes + 1 > _FUSE_MAX_LANES:
        return False
    return (num_lanes + 1) * m * 4 * 3 <= _FUSE_VMEM_BUDGET


def note_dispatch(m: int, num_lanes: int, tiles: int | None = None) -> bool:
    """Host-side metric hook for a sort-engine=pallas dispatch: records the
    pallas{kernels_launched, tiles, fallback_xla} counters from the SAME
    admission predicate the traced kernel uses (the decision is static in
    (m, lanes), so host bookkeeping and trace-time routing cannot drift).
    Returns whether the fused kernel serves the dispatch."""
    from ..metrics import pallas_metrics

    g = pallas_metrics()
    fused = fusable(m, num_lanes)
    g.counter("kernels_launched").inc()
    if fused:
        g.counter("tiles").inc(1 if tiles is None else tiles)
    else:
        # lax.sort fallback still runs the pallas boundary sweep (one grid
        # step per _BLOCK rows) when pallas imports at all
        if _PALLAS_OK:
            g.counter("tiles").inc(max(1, m // _BLOCK) if tiles is None else tiles)
        g.counter("fallback_xla").inc()
    return fused


# ---------------------------------------------------------------------------
# fused sort + run-boundary + keep-last kernel
# ---------------------------------------------------------------------------


def _lex_gt(a, b):
    """Strict lexicographic a > b over the lane axis (axis 0). The caller
    stacks an iota lane last, so tuples are distinct and the order total."""
    gt = jnp.zeros(a.shape[1:], dtype=jnp.bool_)
    eq = jnp.ones(a.shape[1:], dtype=jnp.bool_)
    lanes = a.shape[0]
    for i in range(lanes):
        ai, bi = a[i], b[i]
        gt = gt | (eq & (ai > bi))
        if i + 1 < lanes:
            eq = eq & (ai == bi)
    return gt


def _bitonic_sort_lanes(arr):
    """In-kernel bitonic sort of the columns of arr (L, m) int32 by
    ascending lexicographic row-tuple order; m is a power of two. Each
    (k, j) stage pairs element i with i^j via the reshape view
    (L, m/(2j), 2, j) — the partner of (q, 0, r) is (q, 1, r) — and the
    merge direction comes from bit log2(k) of i, which inside a pair block
    is constant: (q*2j) & k."""
    lanes, m = arr.shape
    k = 2
    while k <= m:
        j = k // 2
        while j >= 1:
            g = m // (2 * j)
            v = arr.reshape(lanes, g, 2, j)
            a = v[:, :, 0, :]
            b = v[:, :, 1, :]
            gt = _lex_gt(a, b)
            q = jax.lax.broadcasted_iota(jnp.int32, (g, j), 0)
            desc = ((q * (2 * j)) & k) != 0
            swap = (gt != desc)[None, :, :]
            na = jnp.where(swap, b, a)
            nb = jnp.where(swap, a, b)
            arr = jnp.concatenate([na[:, :, None, :], nb[:, :, None, :]], axis=2).reshape(
                lanes, m
            )
            j //= 2
        k *= 2
    return arr


@functools.lru_cache(maxsize=None)
def _fused_kernel(num_boundary: int):
    """Kernel body for a given boundary-lane count. Input (L+1, m) int32:
    rows [0, num_boundary) split segments (pad flag + OVC/extra + key
    lanes), rows [num_boundary, L) order within segments only (sequence
    lanes), row L is the iota / permutation carry. Output (3, m) int32:
    row 0 = perm (sorted -> input), row 1 = keep_last (1 at the last row of
    each segment, pad segments included — the sorted_segments contract),
    row 2 = the sorted pad+boundary lane 0 (still sign-flipped; the wrapper
    flips it back)."""

    def kernel(arr_ref, out_ref):
        arr = _bitonic_sort_lanes(arr_ref[...])
        m = arr.shape[1]
        cur = arr[:num_boundary]  # (B, m) sorted segment lanes
        nxt = jnp.concatenate([cur[:, 1:], cur[:, -1:]], axis=1)
        xor = cur ^ nxt
        diff = xor[0:1, :]
        for i in range(1, num_boundary):
            diff = diff | xor[i : i + 1, :]
        keep = jnp.where(diff != 0, jnp.int32(1), jnp.int32(0))  # (1, m)
        # the global last row has no successor: it always closes its segment
        pos = jax.lax.broadcasted_iota(jnp.int32, (1, m), 1)
        keep = jnp.where(pos == m - 1, jnp.int32(1), keep)
        out_ref[0:1, :] = arr[-1:, :]  # perm (the iota lane, sorted)
        out_ref[1:2, :] = keep
        out_ref[2:3, :] = arr[0:1, :]  # sorted pad lane (flipped space)

    return kernel


def _flip(lane):
    """Order-preserving bijection uint{8,16,32} -> int32 (Mosaic compares
    are signed; XOR of the sign bit keeps unsigned order)."""
    return jax.lax.bitcast_convert_type(
        lane.astype(jnp.uint32) ^ jnp.uint32(0x80000000), jnp.int32
    )


def fused_sort_segments(boundary_lanes, order_lanes):
    """The fused inner merge (traced inside a consumer jit): stable sort +
    run-boundary detection + keep-last in one pallas pass.

    boundary_lanes: [(m,) uint] — pad flag first, then OVC/extra keys, then
    key lanes; these both order rows and split segments. order_lanes:
    [(m,) uint] sequence lanes — order within a segment only. Returns the
    sorted_segments contract (pad_sorted, perm, seg_start, keep_last,
    seg_id), bit-identical to the `lax.sort` path."""
    m = boundary_lanes[0].shape[0]
    rows = [_flip(l) for l in list(boundary_lanes) + list(order_lanes)]
    rows.append(jnp.arange(m, dtype=jnp.int32))
    arr = jnp.stack(rows, axis=0)
    out = pl.pallas_call(
        _fused_kernel(len(boundary_lanes)),
        out_shape=jax.ShapeDtypeStruct((3, m), jnp.int32),
        interpret=pallas_interpret(),
    )(arr)
    perm = out[0]
    keep_last = out[1] != 0
    pad_sorted = jax.lax.bitcast_convert_type(out[2], jnp.uint32) ^ jnp.uint32(0x80000000)
    seg_start = jnp.concatenate([jnp.ones((1,), jnp.bool_), keep_last[:-1]])
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    return pad_sorted, perm, seg_start, keep_last, seg_id


# ---------------------------------------------------------------------------
# post-sort boundary sweep (the large-batch fallback)
# ---------------------------------------------------------------------------


def _keep_last_kernel_factory(mask_pad: bool):
    def _keep_last_kernel(cur_ref, nxt_ref, out_ref):
        cur = cur_ref[...]  # (L, B) — stacked pad+key lanes
        nxt = nxt_ref[...]  # (L, B) — the following block (clamped at the end)
        # "next element" of each position: shift left, last column from the
        # lookahead block's first column
        shifted = jnp.concatenate([cur[:, 1:], nxt[:, :1]], axis=1)
        # stay 2D throughout (mosaic wants tiled vectors) and avoid reductions
        # (unsigned reductions are unimplemented): fold lanes with bitwise-or,
        # the lane count is static and small
        xor = cur ^ shifted
        diff = xor[0:1, :]
        for i in range(1, xor.shape[0]):
            diff = diff | xor[i : i + 1, :]
        neq = jnp.where(diff != 0, jnp.uint32(1), jnp.uint32(0))
        if mask_pad:
            not_pad = jnp.where(cur[0:1, :] == 0, jnp.uint32(1), jnp.uint32(0))
            neq = neq * not_pad
        out_ref[...] = neq  # (1, B) uint32

    return _keep_last_kernel


def _sweep_block(m: int) -> tuple[int, int]:
    """(padded size, block) for the boundary sweep: the grid must tile m
    exactly, so non-multiples are padded up — to the next multiple of 128
    under one block, of _BLOCK beyond (the old wrapper silently REQUIRED
    m % 128 == 0 and truncated the tail otherwise)."""
    if m <= _BLOCK:
        m2 = ((m + 127) // 128) * 128
        return m2, m2
    m2 = ((m + _BLOCK - 1) // _BLOCK) * _BLOCK
    return m2, _BLOCK


@functools.partial(jax.jit, static_argnames=("interpret", "mask_pad"))
def keep_last_mask(stacked: jax.Array, interpret: bool = False, mask_pad: bool = True) -> jax.Array:
    """stacked: (L, m) uint32, lane 0 = pad flag, lanes 1.. = key lanes,
    rows sorted. Returns (m,) uint32: 1 where the row is the last of its
    segment (mask_pad=True additionally zeroes pad rows — the legacy dedup
    contract; mask_pad=False returns the raw sorted_segments keep_last,
    where the trailing pad segment closes too). Any m >= 1 is accepted:
    non-multiples of the block are padded inside the wrapper with pad-flag
    rows whose boundary against the true last row closes its segment."""
    l, m = stacked.shape
    m2, block = _sweep_block(m)
    if m2 != m:
        ext = jnp.zeros((l, m2 - m), dtype=stacked.dtype)
        # synthetic pad rows: pad flag set, key lanes zero — they differ
        # from any real last row in lane 0, closing its segment exactly
        ext = ext.at[0, :].set(jnp.uint32(1))
        stacked = jnp.concatenate([stacked, ext], axis=1)
    grid = m2 // block
    last_block = grid - 1

    out = pl.pallas_call(
        _keep_last_kernel_factory(mask_pad),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((l, block), lambda i: (0, i)),
            # lookahead: the next block (the final block reads itself; the
            # wrapper forces the true last element below)
            pl.BlockSpec((l, block), lambda i: (0, jnp.minimum(i + 1, last_block))),
        ],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, m2), jnp.uint32),
        interpret=interpret,
    )(stacked, stacked)
    out = out[0, :m]
    # the global last element has no successor: it always closes its segment
    # (under mask_pad, only when it is not padding)
    if mask_pad:
        last_valid = jnp.where(stacked[0, m - 1] == 0, jnp.uint32(1), jnp.uint32(0))
    else:
        last_valid = jnp.uint32(1)
    return out.at[m - 1].set(last_valid)
