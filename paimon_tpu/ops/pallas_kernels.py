"""Pallas TPU kernels for the merge hot path.

The sort itself stays `lax.sort` (XLA's TPU sort is already tiled onto the
hardware), but the post-sort phase — detecting segment boundaries across all
key lanes at once — is a bandwidth-bound elementwise pass that pallas
expresses as one fused VMEM-resident sweep: each grid step loads a block of
the stacked lanes plus a one-element lookahead (the same operand bound a
second time with a +1 block index map) and emits the keep-last mask directly.

Enabled via table option `sort-engine=pallas` (CoreOptions.SortEngine);
`interpret=True` runs the same kernel on CPU for tests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["keep_last_mask"]

_BLOCK = 2048


def _keep_last_kernel(cur_ref, nxt_ref, out_ref):
    cur = cur_ref[...]  # (L, B) — stacked pad+key lanes
    nxt = nxt_ref[...]  # (L, B) — the following block (clamped at the end)
    # "next element" of each position: shift left, last column from the
    # lookahead block's first column
    shifted = jnp.concatenate([cur[:, 1:], nxt[:, :1]], axis=1)
    # stay 2D throughout (mosaic wants tiled vectors) and avoid reductions
    # (unsigned reductions are unimplemented): fold lanes with bitwise-or,
    # the lane count is static and small
    xor = cur ^ shifted
    diff = xor[0:1, :]
    for i in range(1, xor.shape[0]):
        diff = diff | xor[i : i + 1, :]
    neq = jnp.where(diff != 0, jnp.uint32(1), jnp.uint32(0))
    not_pad = jnp.where(cur[0:1, :] == 0, jnp.uint32(1), jnp.uint32(0))
    out_ref[...] = neq * not_pad  # (1, B) uint32


@functools.partial(jax.jit, static_argnames=("interpret",))
def keep_last_mask(stacked: jax.Array, interpret: bool = False) -> jax.Array:
    """stacked: (L, m) uint32, lane 0 = pad flag, lanes 1.. = key lanes,
    rows sorted. Returns (m,) uint32: 1 where the row is the last of its
    segment and not padding. m must be a multiple of 128 (pad_size ensures
    powers of two >= 128)."""
    l, m = stacked.shape
    block = min(_BLOCK, m)
    grid = m // block
    last_block = grid - 1

    out = pl.pallas_call(
        _keep_last_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((l, block), lambda i: (0, i)),
            # lookahead: the next block (the final block reads itself; the
            # wrapper forces the true last element below)
            pl.BlockSpec((l, block), lambda i: (0, jnp.minimum(i + 1, last_block))),
        ],
        out_specs=pl.BlockSpec((1, block), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, m), jnp.uint32),
        interpret=interpret,
    )(stacked, stacked)
    out = out[0]
    # the global last element has no successor: it always closes its segment
    # (unless it is padding)
    last_valid = jnp.where(stacked[0, m - 1] == 0, jnp.uint32(1), jnp.uint32(0))
    return out.at[m - 1].set(last_valid)
