"""Key-lane compression: prefix truncation, lane packing, offset-value codes.

Every hot path in the system — merge read, compaction rewrite, sort-compact,
changelog dedup — bottoms out in one stable `lax.sort` over uint32 key lanes
(ops/merge.py), and sort cost scales with operand width. This module shrinks
that width with three order- and equality-preserving transforms, decided per
merge from lane statistics (a `LanePlan` alongside `MergePlan`):

  1. PREFIX TRUNCATION — a lane constant across the batch (the batch's shared
     key prefix: common int64 high words, a partition-constant string rank)
     affects neither ordering nor segmentation and is dropped outright.
     Partially-constant lanes are min-shifted so only their varying low bits
     remain (the bit-exact generalization of the old u16/u32 `narrow_lane`
     tiers): a lane spanning [lo, lo+2^b) carries exactly b bits.

  2. LANE PACKING — adjacent truncated lanes whose bit widths sum to <= 32
     fuse into ONE uint32 operand, most-significant lane in the high bits:
     unsigned comparison of the fused operand equals lexicographic comparison
     of its member lanes, and equality of the fused operand equals joint
     equality (the packing is injective because each member is < 2^bits).
     K logical lanes sort as ceil(sum bits / 32) physical operands.

  3. OVC LANES — "Robust and Efficient Sorting with Offset-Value Coding"
     (PAPERS.md) replaces full-key comparisons with (offset, value) codes
     computed once against a shared reference. Every input run of a merge
     (data file / memtable) is already key-sorted, so the batch minimum is
     the min over run heads — a row every input is >= of. Coding each row
     against that base, code = ((G - offset) << vbits) | value where offset
     is the first packed operand differing from the base and value is the
     row's operand there, yields a single uint32 lane with the OVC property:
     where two codes DIFFER, their unsigned order equals the rows' full key
     order; where they are EQUAL, the rows share their prefix through the
     offset operand and the sort falls through to the remaining operands.
     The code is therefore carried through `lax.sort` as the leading key
     (after the pad flag) without changing the output permutation, and
     segment boundary detection tests it FIRST — the overwhelming majority
     of adjacent-row comparisons resolve on the code lane alone instead of
     walking all key lanes. Computed on device (`ovc_codes_jax`) inside the
     merge kernels, with `ovc_codes_np` as the numpy oracle twin.

All three are pure reindexings of the comparator: sort order, tie structure
(stability), and the equal-key segmentation are bit-identical to the
uncompressed path — the parity suite (tests/test_lanes.py) asserts exactly
that across seeds, key shapes, null rates, and collation edge cases.

`merge.lane-compression` (default on) gates the whole layer; the
PAIMON_TPU_LANE_COMPRESSION env var overrides it in either direction so the
verify stages can force both paths.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

__all__ = [
    "LanePlan",
    "plan_lanes",
    "plan_lanes_from_stats",
    "plan_lanes_global",
    "lane_stats",
    "apply_plan",
    "compress_key_lanes",
    "resolve_compress",
    "ovc_codes_np",
    "ovc_codes_jax",
    "scalar_dedup_winner",
]

# an OVC lane only pays when the packed key is still wide: G >= this many
# fused operands (a 1-operand key IS its own complete offset-value code)
_OVC_MIN_GROUPS = 2


@dataclass(frozen=True)
class LanePlan:
    """The per-merge compression decision over one (n, K) uint32 lane matrix.

    keep/los/bits describe truncation (kept original lane index, subtracted
    minimum, exact bit width after the shift); groups lists, per fused output
    operand, the positions INTO the kept sequence it packs (consecutive, in
    order, most-significant first). use_ovc adds the leading offset-value
    code lane, coded against `base` (the packed values of the batch's
    lexicographically minimal row) with a vbits-wide value field."""

    lanes_in: int
    keep: tuple[int, ...]
    los: tuple[int, ...]
    bits: tuple[int, ...]
    groups: tuple[tuple[int, ...], ...]
    use_ovc: bool = False
    ovc_vbits: int = 0
    base: tuple[int, ...] = ()

    @property
    def lanes_out(self) -> int:
        """Physical uint32 operands uploaded to the sort."""
        return len(self.groups)

    @property
    def sort_width(self) -> int:
        """Key operands the sort actually compares (incl. the OVC lane)."""
        return len(self.groups) + (1 if self.use_ovc else 0)

    @property
    def is_identity(self) -> bool:
        """True when applying the plan would be a no-op reshape: every lane
        kept, unshifted, alone in its group, no OVC."""
        return (
            not self.use_ovc
            and len(self.groups) == self.lanes_in
            and all(lo == 0 for lo in self.los)
            and all(len(g) == 1 for g in self.groups)
        )

    def upload_bytes_per_row(self) -> int:
        """Link bytes per row after the downstream u16/u32 narrowing tiers
        (ops/merge.narrow_lane picks u16 when a group's range fits)."""
        return sum(2 if sum(self.bits[p] for p in g) <= 16 else 4 for g in self.groups)


def resolve_compress(compress: bool | None) -> bool:
    """One resolution order everywhere: the PAIMON_TPU_LANE_COMPRESSION env
    var (verify stages force both paths) beats the caller's option value,
    which beats the default (on)."""
    env = os.environ.get("PAIMON_TPU_LANE_COMPRESSION", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if compress is not None:
        return bool(compress)
    return True


def lane_stats(key_lanes: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-lane (min, max) over one shard's rows — the commutative piece a
    mesh merge reduces across shards before planning (plan_lanes_global).
    Zero-row shards contribute the neutral element (max sentinel mins, zero
    maxes), so reducing over them never widens a lane."""
    key_lanes = np.ascontiguousarray(key_lanes)
    n, k = key_lanes.shape
    if n == 0:
        return (
            np.full(k, 0xFFFFFFFF, dtype=np.uint32),
            np.zeros(k, dtype=np.uint32),
        )
    return key_lanes.min(axis=0), key_lanes.max(axis=0)


def _truncate_and_group(k: int, los, his):
    """The shared stats -> (keep, bits, lo_kept, groups, vbits) decision of
    every planner entry point: drop constant lanes, width each survivor to
    its exact ptp bit length, fuse adjacent widths into <=32-bit operands."""
    keep: list[int] = []
    bits: list[int] = []
    lo_kept: list[int] = []
    for i in range(k):
        ptp = int(his[i]) - int(los[i])
        if ptp:
            keep.append(i)
            bits.append(ptp.bit_length())
            lo_kept.append(int(los[i]))
    groups: list[tuple[int, ...]] = []
    cur: list[int] = []
    cur_bits = 0
    for pos, b in enumerate(bits):
        if cur and cur_bits + b > 32:
            groups.append(tuple(cur))
            cur, cur_bits = [], 0
        cur.append(pos)
        cur_bits += b
    if cur:
        groups.append(tuple(cur))
    vbits = max((sum(bits[p] for p in grp) for grp in groups), default=0)
    return keep, bits, lo_kept, groups, vbits


def plan_lanes_from_stats(lanes_in: int, los, his) -> LanePlan:
    """Truncation + packing decided from per-lane (min, max) ALONE — the
    stats may have been reduced over many shards (plan_lanes_global), so
    every shard of one mesh merge derives identical packed widths and the
    packed operands stay comparable across devices (range-shuffle splitters,
    stacked shard_map lanes). Never emits an OVC lane: the code needs the
    batch-min row, and the mesh kernels carry plain packed lanes."""
    keep, bits, lo_kept, groups, _vbits = _truncate_and_group(lanes_in, los, his)
    if all(len(grp) == 1 for grp in groups):
        # same zero-shift rule as the local planner: a pure column selection
        lo_kept = [0] * len(lo_kept)
    return LanePlan(lanes_in, tuple(keep), tuple(lo_kept), tuple(bits), tuple(groups))


def plan_lanes_global(parts) -> LanePlan:
    """ONE LanePlan for a whole mesh merge: reduce per-shard lane stats and
    plan from the reduction (ISSUE 7 satellite: per-shard plans can disagree
    on packed widths across devices — a lane spanning 8 bits on shard A and
    20 on shard B packs differently, and the stacked shard_map lanes or the
    range-shuffle splitters would then compare apples to oranges). Every
    shard applies THIS plan via apply_plan."""
    parts = [np.ascontiguousarray(p) for p in parts]
    k = parts[0].shape[1] if parts else 0
    if not parts or all(p.shape[0] == 0 for p in parts):
        return LanePlan(k, (), (), (), ())
    los = None
    his = None
    for p in parts:
        lo, hi = lane_stats(p)
        los = lo if los is None else np.minimum(los, lo)
        his = hi if his is None else np.maximum(his, hi)
    return plan_lanes_from_stats(k, los, his)


def plan_lanes(key_lanes: np.ndarray, enable_ovc: bool = True) -> LanePlan:
    """Decide truncation, packing, and OVC from one pass of lane stats.
    O(K * n) host work — the same order as the boundary compares it saves."""
    key_lanes = np.ascontiguousarray(key_lanes)
    n, k = key_lanes.shape
    if n <= 1 or k == 0:
        # 0/1 rows: every lane is batch-constant — a zero-width key
        return LanePlan(k, (), (), (), ())
    los, his = lane_stats(key_lanes)
    keep, bits, lo_kept, groups, vbits = _truncate_and_group(k, los, his)
    g = len(groups)
    use_ovc = enable_ovc and g >= _OVC_MIN_GROUPS and g.bit_length() + vbits <= 32
    if not use_ovc and all(len(grp) == 1 for grp in groups):
        # nothing fuses and no code lane needs a bounded value field: the
        # min-shift would be a pure copy (order and equality are shift-
        # invariant, and the upload tier re-shifts in narrow_lane anyway) —
        # zero the shifts so apply_plan can take the no-arithmetic path
        lo_kept = [0] * len(lo_kept)
    base: tuple[int, ...] = ()
    if use_ovc:
        # the batch's lexicographically minimal row (over kept lanes), found
        # by iterative masking; its packed values are the shared OVC base —
        # a row every input row compares >= to, which is what makes the code
        # order-consistent
        mask = np.ones(n, dtype=np.bool_)
        min_vals: list[int] = []
        for i in keep:
            col = key_lanes[:, i]
            mval = int(col[mask].min())
            mask &= col == np.uint32(mval)
            min_vals.append(mval)
        packed_base = []
        for grp in groups:
            acc = 0
            for pos in grp:
                acc = (acc << bits[pos]) | (min_vals[pos] - lo_kept[pos])
            packed_base.append(acc)
        base = tuple(packed_base)
    return LanePlan(
        k, tuple(keep), tuple(lo_kept), tuple(bits), tuple(groups),
        use_ovc, vbits if use_ovc else 0, base,
    )


def apply_plan(plan: LanePlan, key_lanes: np.ndarray) -> np.ndarray:
    """(n, K) uint32 -> (n, lanes_out) uint32: shift and fuse per the plan.
    Order-, equality-, and stability-preserving by construction (see module
    docstring); the numpy half of the transform — the OVC lane is computed
    from THIS output, on device in the kernels or via ovc_codes_np on the
    oracle path."""
    key_lanes = np.ascontiguousarray(key_lanes)
    n = key_lanes.shape[0]
    if all(len(g) == 1 for g in plan.groups) and not any(plan.los):
        # pure truncation: a column selection, no per-row arithmetic
        if len(plan.groups) == plan.lanes_in:
            return key_lanes.astype(np.uint32, copy=False)
        sel = [plan.keep[g[0]] for g in plan.groups]
        return np.ascontiguousarray(key_lanes[:, sel].astype(np.uint32, copy=False))
    out = np.empty((n, len(plan.groups)), dtype=np.uint32)
    for gi, grp in enumerate(plan.groups):
        first = grp[0]
        acc = key_lanes[:, plan.keep[first]].astype(np.uint32) - np.uint32(plan.los[first])
        for pos in grp[1:]:
            lane = key_lanes[:, plan.keep[pos]].astype(np.uint32) - np.uint32(plan.los[pos])
            acc = (acc << np.uint32(plan.bits[pos])) | lane
        out[:, gi] = acc
    return out


def compress_key_lanes(
    key_lanes: np.ndarray,
    compress: bool | None = None,
    enable_ovc: bool = True,
) -> tuple[np.ndarray, LanePlan | None]:
    """The one seam every consumer calls: returns (lanes', plan) where lanes'
    is the compressed (n, G) matrix, or (lanes, None) unchanged when the
    layer is off. Records the lanes{...} metric group per planned merge."""
    if not resolve_compress(compress):
        return key_lanes, None
    key_lanes = np.ascontiguousarray(key_lanes)
    plan = plan_lanes(key_lanes, enable_ovc=enable_ovc)
    packed = apply_plan(plan, key_lanes)
    _record(plan, key_lanes.shape[0])
    return packed, plan


def _record(plan: LanePlan, n: int) -> None:
    from ..metrics import lanes_metrics

    g = lanes_metrics()
    g.counter("plans").inc()
    g.counter("lanes_in").inc(plan.lanes_in)
    g.counter("lanes_out").inc(plan.sort_width)
    if plan.use_ovc:
        g.counter("ovc_merges").inc()
    g.counter("bytes_saved").inc(max(0, n * (4 * plan.lanes_in - plan.upload_bytes_per_row())))


# ---------------------------------------------------------------------------
# offset-value codes
# ---------------------------------------------------------------------------

def ovc_codes_np(packed: np.ndarray, base, vbits: int) -> np.ndarray:
    """Numpy oracle of the OVC kernel: packed (n, G) uint32 operands, base
    (G,) packed values of a row <= every input row. Returns (n,) uint32
    codes ((G - offset) << vbits) | value; a row equal to the base codes 0."""
    packed = np.ascontiguousarray(packed, dtype=np.uint32)
    n, g = packed.shape
    base = np.asarray(base, dtype=np.uint32)
    eq = packed == base[None, :]
    prefix = np.cumprod(eq, axis=1).astype(bool)  # still-equal through lane j
    offset = prefix.sum(axis=1).astype(np.int64)  # first differing operand; G = equal
    first_diff = np.minimum(offset, g - 1)
    value = packed[np.arange(n), first_diff]
    value = np.where(offset < g, value, np.uint32(0)).astype(np.uint32)
    return ((np.uint32(g) - offset.astype(np.uint32)) << np.uint32(vbits)) | value


def ovc_codes_jax(lanes, base, vbits: int):
    """Device twin of ovc_codes_np, traced inside the merge kernels: lanes is
    a sequence of (m,) uint arrays (possibly narrowed u16 — upcast is free on
    device), base a (G,) uint32 array. Pad rows produce one shared (garbage)
    code; the pad flag leads both the sort and the boundary compare, so pad
    codes never order or segment anything."""
    import jax.numpy as jnp

    g = len(lanes)
    m = lanes[0].shape[0]
    eq_run = jnp.ones(m, dtype=jnp.bool_)
    offset = jnp.zeros(m, dtype=jnp.uint32)
    value = jnp.zeros(m, dtype=jnp.uint32)
    for j in range(g):
        l32 = lanes[j].astype(jnp.uint32)
        bj = base[j].astype(jnp.uint32)
        first_diff = eq_run & (l32 != bj)
        value = jnp.where(first_diff, l32, value)
        eq_run = eq_run & (l32 == bj)
        offset = offset + eq_run.astype(jnp.uint32)
    return ((jnp.uint32(g) - offset) << jnp.uint32(vbits)) | value


# ---------------------------------------------------------------------------
# zero-width fast path
# ---------------------------------------------------------------------------

def scalar_dedup_winner(seq_lanes: np.ndarray | None, n: int) -> np.ndarray:
    """All keys equal (every lane batch-constant): dedup degenerates to ONE
    winner — the last row in (sequence lanes, input order). No key sort, no
    device trip; the zero-width scalar fast path of ISSUE 6."""
    if n == 0:
        return np.empty(0, dtype=np.int32)
    if seq_lanes is None or seq_lanes.shape[1] == 0:
        return np.array([n - 1], dtype=np.int32)
    order = np.lexsort([seq_lanes[:, i] for i in range(seq_lanes.shape[1] - 1, -1, -1)])
    return order[-1:].astype(np.int32)
