"""Point lookups on primary-key tables.

Parity: /root/reference/paimon-common/.../lookup/hash/ (HashLookupStoreWriter/
Reader — immutable on-disk hash KV files with optional bloom filters),
paimon-core/.../mergetree/LookupLevels.java:64 (pull a remote LSM file into a
local lookup file, cache with size-based eviction, point-query levels) and
table/query/LocalTableQuery.java:55.

Here a "lookup file" is the data file's rows plus a sorted key-hash index —
probes are vectorized (one searchsorted per batch of keys, then exact-key
verification), and the cache is LRU by resident bytes.
"""

from collections import OrderedDict
from typing import Sequence

import numpy as np

from ..core.datafile import DataFileMeta, KeyValueFileReaderFactory
from ..core.kv import KVBatch
from ..table.bucket import key_hashes as _key_hashes_of  # single hash definition
from ..types import RowKind

__all__ = ["LookupFile", "LookupFileCache", "LookupLevels"]


class LookupFile:
    """One data file, indexed for point probes. Persistable as an immutable
    on-disk hash store — arrow IPC rows + sorted hash/row-id sidecar arrays
    (reference HashLookupStoreWriter/Reader: the same shape, a hash table
    over serialized rows, written once and mmap-read)."""

    def __init__(
        self,
        kv: KVBatch,
        key_names: Sequence[str],
        bloom_fpp: float | None = None,
        hash_load_factor: float | None = None,
    ):
        self.kv = kv
        self.key_names = list(key_names)
        hashes = _key_hashes_of(kv.data, key_names)
        self.order = np.argsort(hashes, kind="stable").astype(np.int32)
        self.sorted_hashes = hashes[self.order]
        self._build_accel(bloom_fpp, hash_load_factor)

    def _build_accel(self, bloom_fpp: float | None, hash_load_factor: float | None) -> None:
        """Probe accelerators (reference HashLookupStoreWriter): an optional
        bloom over the key hashes (lookup.cache.bloom.filter.*) and a radix
        slot table sized n/load-factor (lookup.hash-load-factor) that turns
        the binary search into a one-bucket scan."""
        n = len(self.sorted_hashes)
        self.bloom = None
        if bloom_fpp is not None and n:
            from ..format.fileindex import BloomFilter

            self.bloom = BloomFilter.for_items(n, bloom_fpp)
            self.bloom.add_hashes(self.sorted_hashes)
        self.slot_shift = None
        if hash_load_factor is not None and n:
            slots = 1
            while slots < int(n / max(hash_load_factor, 0.1)):
                slots <<= 1
            self.slot_shift = max(64 - slots.bit_length() + 1, 0)
            # slot boundaries: first sorted position whose hash prefix >= s
            prefixes = (self.sorted_hashes >> np.uint64(self.slot_shift)).astype(np.uint64)
            self.slot_starts = np.searchsorted(prefixes, np.arange(slots + 1, dtype=np.uint64))

    def save(self, file_io, path: str) -> None:
        """Persist rows + index: `<path>` (arrow IPC) and `<path>.hidx`."""
        import io as _io

        import pyarrow as pa

        buf = _io.BytesIO()
        table = self.kv.to_disk_batch().to_arrow()
        with pa.ipc.new_stream(buf, table.schema) as w:
            w.write_table(table)
        file_io.write_bytes(path, buf.getvalue(), overwrite=True)
        idx = self.sorted_hashes.tobytes() + self.order.tobytes()
        file_io.write_bytes(f"{path}.hidx", idx, overwrite=True)

    @staticmethod
    def load(
        file_io,
        path: str,
        value_schema,
        key_names: Sequence[str],
        bloom_fpp: float | None = None,
        hash_load_factor: float | None = None,
    ) -> "LookupFile":
        import io as _io

        import pyarrow as pa

        from ..core.kv import KVBatch as _KVBatch
        from ..data.batch import ColumnBatch

        reader = pa.ipc.open_stream(_io.BytesIO(file_io.read_bytes(path)))
        table = reader.read_all()
        from ..core.kv import kv_disk_schema

        disk = ColumnBatch.from_arrow(table, kv_disk_schema(value_schema))
        kv = _KVBatch.from_disk_batch(disk, value_schema)
        lf = LookupFile.__new__(LookupFile)
        lf.kv = kv
        lf.key_names = list(key_names)
        raw = file_io.read_bytes(f"{path}.hidx")
        n = kv.num_rows
        lf.sorted_hashes = np.frombuffer(raw[: n * 8], dtype=np.uint64).copy()
        lf.order = np.frombuffer(raw[n * 8 : n * 8 + n * 4], dtype=np.int32).copy()
        lf._build_accel(bloom_fpp, hash_load_factor)
        return lf

    @property
    def num_bytes(self) -> int:
        total = 0
        for c in self.kv.data.columns.values():
            total += c.values.nbytes if c.values.dtype != np.dtype(object) else len(c.values) * 32
        return total + self.sorted_hashes.nbytes + self.order.nbytes

    def probe(self, key_tuple: tuple, key_hash: np.uint64):
        """Latest row for the key in this file, or None. Files have unique
        keys, so at most one row matches (hash collisions verified exactly)."""
        if self.bloom is not None and not bool(
            self.bloom.might_contain_hashes(np.asarray([key_hash], dtype=np.uint64))[0]
        ):
            return None
        if self.slot_shift is not None:
            s = int(key_hash >> np.uint64(self.slot_shift))
            b_lo, b_hi = int(self.slot_starts[s]), int(self.slot_starts[s + 1])
            seg = self.sorted_hashes[b_lo:b_hi]
            lo = b_lo + int(np.searchsorted(seg, key_hash, side="left"))
            hi = b_lo + int(np.searchsorted(seg, key_hash, side="right"))
        else:
            lo = int(np.searchsorted(self.sorted_hashes, key_hash, side="left"))
            hi = int(np.searchsorted(self.sorted_hashes, key_hash, side="right"))
        for i in range(lo, hi):
            row = int(self.order[i])
            if all(self.kv.data.column(k).values[row] == v for k, v in zip(self.key_names, key_tuple)):
                return row
        return None


class LookupFileCache:
    """LRU by resident bytes (reference LookupLevels' caffeine cache with a
    file-size weigher :137-158)."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._cache: OrderedDict[str, LookupFile] = OrderedDict()
        self._bytes = 0

    def get(self, file_name: str, loader) -> LookupFile:
        if file_name in self._cache:
            self._cache.move_to_end(file_name)
            return self._cache[file_name]
        lf = loader()
        self._cache[file_name] = lf
        self._bytes += lf.num_bytes
        while self._bytes > self.max_bytes and len(self._cache) > 1:
            _, evicted = self._cache.popitem(last=False)
            self._bytes -= evicted.num_bytes
        return lf

    def invalidate(self, file_name: str) -> None:
        lf = self._cache.pop(file_name, None)
        if lf is not None:
            self._bytes -= lf.num_bytes


class LookupLevels:
    """Point lookup across one bucket's LSM levels: level-0 newest-first,
    then each level's single sorted run located by key range."""

    def __init__(
        self,
        files: list[DataFileMeta],
        reader_factory: KeyValueFileReaderFactory,
        key_names: Sequence[str],
        cache: LookupFileCache | None = None,
        deletion_vectors: dict | None = None,
        local_store_dir: str | None = None,
        file_io=None,
        bloom_fpp: float | None = None,
        hash_load_factor: float | None = None,
        max_disk_bytes: int | None = None,
        file_retention_millis: int | None = None,
    ):
        from ..core.levels import Levels

        self.levels = Levels(files, num_levels=max((f.level for f in files), default=0) + 1)
        self.reader_factory = reader_factory
        self.key_names = list(key_names)
        self.cache = cache or LookupFileCache()
        self.deletion_vectors = deletion_vectors or {}
        # optional disk tier: converted lookup files persist locally so a
        # restart (or memory-cache eviction) re-reads the local store instead
        # of the remote data file (reference LookupLevels.createLookupFile)
        self.local_store_dir = local_store_dir
        self.file_io = file_io
        self.bloom_fpp = bloom_fpp
        self.hash_load_factor = hash_load_factor
        self.max_disk_bytes = max_disk_bytes
        self.file_retention_millis = file_retention_millis

    def _sweep_local_store(self) -> None:
        """Disk-tier hygiene (reference lookup.cache-max-disk-size /
        lookup.cache-file-retention): persisted lookup files are re-buildable
        caches, so drop expired ones and the oldest past the byte budget."""
        if not (self.local_store_dir and self.file_io):
            return
        try:
            stats = [
                s
                for s in self.file_io.list_status(self.local_store_dir)
                if s.path.endswith(".lookup") or s.path.endswith(".hidx")
            ]
        except (FileNotFoundError, OSError):
            return
        import time

        now_ms = time.time() * 1000
        # group .lookup + .hidx as ONE logical entry: evicting half a pair
        # leaves a .lookup whose load crashes on the missing .hidx
        pairs: dict[str, list] = {}
        for s in stats:
            stem = s.path[: -len(".hidx")] if s.path.endswith(".hidx") else s.path
            pairs.setdefault(stem, []).append(s)
        keep = []
        for stem, members in pairs.items():
            mtime = max(
                getattr(s, "mtime_millis", None) or getattr(s, "modification_time", 0)
                for s in members
            )
            if (
                self.file_retention_millis is not None
                and mtime
                and now_ms - mtime > self.file_retention_millis
            ):
                for s in members:
                    self.file_io.delete(s.path)
            else:
                keep.append((mtime, members))
        if self.max_disk_bytes is not None:
            total = sum(s.size for _, members in keep for s in members)
            for _, members in sorted(keep, key=lambda t: t[0]):  # oldest pair first
                if total <= self.max_disk_bytes:
                    break
                for s in members:
                    self.file_io.delete(s.path)
                    total -= s.size

    def _load(self, meta: DataFileMeta) -> LookupFile:
        local = (
            f"{self.local_store_dir}/{meta.file_name}.lookup" if self.local_store_dir and self.file_io else None
        )
        has_dv = meta.file_name in self.deletion_vectors
        if local and not has_dv and self.file_io.exists(local):
            return LookupFile.load(
                self.file_io, local, self.reader_factory.read_schema, self.key_names,
                self.bloom_fpp, self.hash_load_factor,
            )
        kv = self.reader_factory.read(meta)
        dv = self.deletion_vectors.get(meta.file_name)
        if dv is not None:
            mask = ~dv.deleted_mask(kv.num_rows)
            if not mask.all():
                kv = kv.filter(mask)
        lf = LookupFile(kv, self.key_names, self.bloom_fpp, self.hash_load_factor)
        if local and not has_dv:  # DV'd files change between snapshots
            self._sweep_local_store()
            lf.save(self.file_io, local)
        return lf

    def _lookup_file(self, meta: DataFileMeta) -> LookupFile:
        return self.cache.get(meta.file_name, lambda: self._load(meta))

    def lookup(self, key_tuple: tuple):
        """Merged latest value row for the key (None if absent or deleted)."""
        from ..data.batch import ColumnBatch

        key_schema = self.reader_factory.read_schema.project(self.key_names)
        probe = ColumnBatch.from_pydict(key_schema, {k: [v] for k, v in zip(self.key_names, key_tuple)})
        h = _key_hashes_of(probe, self.key_names)[0]
        # level 0: newest first by sequence
        for meta in self.levels.level0:
            if meta.min_key <= key_tuple <= meta.max_key:
                row = self._lookup_file(meta).probe(key_tuple, h)
                if row is not None:
                    return self._result(meta, row)
        for lv in sorted(self.levels.runs):
            run = self.levels.runs[lv]
            meta = self._file_for_key(run.files, key_tuple)
            if meta is not None:
                row = self._lookup_file(meta).probe(key_tuple, h)
                if row is not None:
                    return self._result(meta, row)
        return None

    def _result(self, meta: DataFileMeta, row: int):
        lf = self._lookup_file(meta)
        kind = RowKind(int(lf.kv.kind[row]))
        if kind in (RowKind.DELETE, RowKind.UPDATE_BEFORE):
            return None
        return lf.kv.data.slice(row, row + 1)

    @staticmethod
    def _file_for_key(files: list[DataFileMeta], key_tuple: tuple) -> DataFileMeta | None:
        lo, hi = 0, len(files) - 1
        while lo <= hi:
            mid = (lo + hi) // 2
            f = files[mid]
            if key_tuple < f.min_key:
                hi = mid - 1
            elif key_tuple > f.max_key:
                lo = mid + 1
            else:
                return f
        return None
