"""Full-cache lookup tables for lookup joins.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../lookup/
FullCacheLookupTable.java:69 and its three shapes — PrimaryKeyLookupTable
(join key = primary key), SecondaryIndexLookupTable (join key is a non-PK
projection, kept as an index into the primary map), NoPrimaryKeyLookupTable
(append table: multimap). The reference streams the table into local RocksDB
and refreshes by snapshot follow-up; here the local store is host dicts over
ColumnBatches and refresh() drains the same streaming scan the changelog
consumers use (+I/+U apply, -U/-D retract).

Caching: bootstrap and refresh reads go through the store's reader factory,
so decoded data files land in (and are served from) the process-wide
data-file cache (utils.cache) — a lookup table bootstrapping next to a query
workload, or several lookup tables over one physical table, decode each
immutable file once. Snapshot expiry invalidates through the same subsystem.

Vectorized probes (ISSUE 12): `get_batch` and `lookup_join` replace the
per-row `get` loop for enrichment reads — the cached state becomes one
ColumnBatch plus a `JoinIndex` (ops/join.py: key lanes encoded once per
refresh epoch, folded to <= 64-bit codes, sorted once), and a whole probe
batch pays one searchsorted instead of one dict probe per row. The scalar
`get` is a thin wrapper over the same index, parity-pinned against the
legacy dict semantics.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Mapping, Sequence

from ..types import RowKind

if TYPE_CHECKING:
    from ..data.batch import ColumnBatch
    from ..table import FileStoreTable

__all__ = ["FullCacheLookupTable", "lookup_join"]


class FullCacheLookupTable:
    """Cache the WHOLE table locally, refresh incrementally, answer point
    lookups by join key."""

    def __init__(self, table: "FileStoreTable", join_keys: Sequence[str] | None = None):
        self.table = table
        pks = list(table.primary_keys)
        self.join_keys = list(join_keys) if join_keys else list(pks)
        unknown = [k for k in self.join_keys if k not in table.row_type]
        if unknown:
            raise ValueError(f"unknown join keys {unknown}")
        self.field_names = table.row_type.field_names
        # shape selection (reference FullCacheLookupTable.create)
        if not pks:
            self.mode = "no-pk"  # multimap join-key -> rows
        elif self.join_keys == pks:
            self.mode = "primary"  # join-key -> row
        else:
            self.mode = "secondary"  # join-key -> {pk} -> row
        self._rows: dict[tuple, tuple] = {}  # pk -> row (primary/secondary)
        self._multi: dict[tuple, list[tuple]] = {}  # join-key -> rows (no-pk)
        self._index: dict[tuple, set[tuple]] = {}  # join-key -> pks (secondary)
        self._pk_idx = [self.field_names.index(k) for k in pks]
        self._jk_idx = [self.field_names.index(k) for k in self.join_keys]
        self._scan = table.new_read_builder().new_stream_scan()
        self._read = table.new_read_builder().new_read()
        # vectorized probe state (ISSUE 12): rebuilt lazily after any change
        self._join_idx = None
        self._state: "ColumnBatch | None" = None
        self.refresh()

    # ---- load / refresh -------------------------------------------------
    def refresh(self) -> int:
        """Drain pending snapshots from the streaming scan (reference:
        FullCacheLookupTable.refresh polls the stream for new snapshots).
        Returns the number of change rows applied."""
        applied = 0
        while True:
            splits = self._scan.plan()
            if not splits:
                return applied
            for split in splits:
                rows, kinds = self._read_changes(split)
                for row, kind in zip(rows, kinds):
                    self._apply(row, kind)
                    applied += 1

    def _read_changes(self, split):
        """Rows + kinds of one split at the KeyValue level: -D rows must
        SURVIVE the read so the cache can retract them (the reference's
        LookupStreamingReader reads deltas unmerged for the same reason)."""
        if getattr(split, "is_changelog", False):
            data, kinds = self._read.read_with_kinds(split)
            return data.to_pylist(), kinds.tolist()
        from ..core.read import MergeFileSplitRead

        store = self.table.store
        read = MergeFileSplitRead(
            store.reader_factory(split.partition, split.bucket),
            store.merge_executor(),
            store.key_names,
        )
        kv = read.read_kv(split.files, drop_delete=False)
        return kv.data.to_pylist(), kv.kind.tolist()

    def _apply(self, row: tuple, kind: int) -> None:
        self._join_idx = None  # any change invalidates the vectorized view
        self._state = None
        add = kind in (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER))
        jk = tuple(row[i] for i in self._jk_idx)
        if self.mode == "no-pk":
            if add:
                self._multi.setdefault(jk, []).append(row)
            else:
                rows = self._multi.get(jk)
                if rows and row in rows:
                    rows.remove(row)
            return
        pk = tuple(row[i] for i in self._pk_idx)
        if self.mode == "secondary":
            old = self._rows.get(pk)
            if old is not None:
                old_jk = tuple(old[i] for i in self._jk_idx)
                s = self._index.get(old_jk)
                if s is not None:
                    s.discard(pk)
        if add:
            self._rows[pk] = row
            if self.mode == "secondary":
                self._index.setdefault(jk, set()).add(pk)
        else:
            self._rows.pop(pk, None)

    # ---- vectorized state ----------------------------------------------
    def state_batch(self) -> "ColumnBatch":
        """The cached table state as ONE ColumnBatch (deterministic order:
        primary/secondary = pk-map insertion order, no-pk = per-key append
        order in key insertion order). Rebuilt lazily per refresh epoch."""
        if self._state is None:
            from ..data.batch import ColumnBatch

            if self.mode == "no-pk":
                rows = [r for rs in self._multi.values() for r in rs]
            else:
                rows = list(self._rows.values())
            self._state = ColumnBatch.from_pylist(self.table.row_type, rows)
        return self._state

    def _join_index(self):
        if self._join_idx is None:
            from ..ops.join import JoinIndex

            self._join_idx = JoinIndex(self.state_batch(), self.join_keys)
        return self._join_idx

    def _probe_batch(self, keys) -> "ColumnBatch":
        """Normalize probe input: a ColumnBatch carrying the join-key
        columns, a {column: sequence} mapping, or a sequence of key tuples."""
        from ..data.batch import ColumnBatch

        if hasattr(keys, "schema") and hasattr(keys, "columns"):
            return keys
        schema = self.table.row_type.project(self.join_keys)
        if isinstance(keys, Mapping):
            return ColumnBatch.from_pydict(schema, {k: keys[k] for k in self.join_keys})
        rows = [tuple(k) if isinstance(k, (tuple, list)) else (k,) for k in keys]
        return ColumnBatch.from_pylist(schema, rows)

    # ---- lookup ---------------------------------------------------------
    def get_batch(self, keys, how: str = "inner"):
        """Vectorized probe: rows whose join key matches each probe key,
        probe-major (each probe key's matches are contiguous, in state
        order). Returns (matched rows as a ColumnBatch of the table's row
        type, probe-row indices aligned with it). how='left' additionally
        keeps unmatched probe keys as all-NULL rows."""
        probe = self._probe_batch(keys)
        res = self._join_index().probe(probe, self.join_keys, how=how)
        state = self.state_batch()
        if how == "left":
            from ..ops.join import materialize_join

            pairs = [(n, n) for n in state.schema.field_names]
            return materialize_join(probe, state, res, [], pairs), res.left_take
        import numpy as np

        return state.take(np.asarray(res.right_take)), res.left_take

    def get(self, key: tuple | Sequence) -> list[tuple]:
        """Rows whose join key equals `key` (a tuple aligned with join_keys)
        — a thin wrapper over the vectorized get_batch. NULL key components
        never match under join semantics, so those keys keep the legacy
        dict probe (None == None)."""
        key = tuple(key)
        if any(k is None for k in key):
            return self._legacy_get(key)
        batch, _ = self.get_batch([key])
        rows = batch.to_pylist()
        if self.mode == "secondary":
            # legacy contract: secondary matches come back sorted by pk
            rows.sort(key=lambda r: tuple(r[i] for i in self._pk_idx))
        return rows

    def _legacy_get(self, key: tuple) -> list[tuple]:
        if self.mode == "no-pk":
            return list(self._multi.get(key, ()))
        if self.mode == "primary":
            row = self._rows.get(key)
            return [row] if row is not None else []
        pks = self._index.get(key, ())
        return [self._rows[pk] for pk in sorted(pks) if pk in self._rows]

    def __len__(self) -> int:
        if self.mode == "no-pk":
            return sum(len(v) for v in self._multi.values())
        return len(self._rows)


def lookup_join(
    lookup: FullCacheLookupTable,
    probe: "ColumnBatch",
    probe_keys: Sequence[str] | None = None,
    suffix: str = "_lookup",
) -> "ColumnBatch":
    """Vectorized enrichment read (the batch replacement for the reference's
    per-row lookup-join operator): LEFT-join `probe` against the cached
    table on its join keys, appending every table column (names colliding
    with probe columns get `suffix`). Probe rows with no match keep NULL
    enrichment columns; a multimap (no-pk) table may fan one probe row out
    to several output rows."""
    keys = list(probe_keys) if probe_keys is not None else list(lookup.join_keys)
    from ..ops.join import materialize_join

    res = lookup._join_index().probe(probe, keys, how="left")
    state = lookup.state_batch()
    left_pairs = [(n, n) for n in probe.schema.field_names]
    right_pairs = [
        (n, n if n not in probe.schema else f"{n}{suffix}")
        for n in state.schema.field_names
    ]
    return materialize_join(probe, state, res, left_pairs, right_pairs)
