"""Full-cache lookup tables for lookup joins.

Parity: /root/reference/paimon-flink/paimon-flink-common/.../lookup/
FullCacheLookupTable.java:69 and its three shapes — PrimaryKeyLookupTable
(join key = primary key), SecondaryIndexLookupTable (join key is a non-PK
projection, kept as an index into the primary map), NoPrimaryKeyLookupTable
(append table: multimap). The reference streams the table into local RocksDB
and refreshes by snapshot follow-up; here the local store is host dicts over
ColumnBatches and refresh() drains the same streaming scan the changelog
consumers use (+I/+U apply, -U/-D retract).

Caching: bootstrap and refresh reads go through the store's reader factory,
so decoded data files land in (and are served from) the process-wide
data-file cache (utils.cache) — a lookup table bootstrapping next to a query
workload, or several lookup tables over one physical table, decode each
immutable file once. Snapshot expiry invalidates through the same subsystem.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Sequence

from ..types import RowKind

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = ["FullCacheLookupTable"]


class FullCacheLookupTable:
    """Cache the WHOLE table locally, refresh incrementally, answer point
    lookups by join key."""

    def __init__(self, table: "FileStoreTable", join_keys: Sequence[str] | None = None):
        self.table = table
        pks = list(table.primary_keys)
        self.join_keys = list(join_keys) if join_keys else list(pks)
        unknown = [k for k in self.join_keys if k not in table.row_type]
        if unknown:
            raise ValueError(f"unknown join keys {unknown}")
        self.field_names = table.row_type.field_names
        # shape selection (reference FullCacheLookupTable.create)
        if not pks:
            self.mode = "no-pk"  # multimap join-key -> rows
        elif self.join_keys == pks:
            self.mode = "primary"  # join-key -> row
        else:
            self.mode = "secondary"  # join-key -> {pk} -> row
        self._rows: dict[tuple, tuple] = {}  # pk -> row (primary/secondary)
        self._multi: dict[tuple, list[tuple]] = {}  # join-key -> rows (no-pk)
        self._index: dict[tuple, set[tuple]] = {}  # join-key -> pks (secondary)
        self._pk_idx = [self.field_names.index(k) for k in pks]
        self._jk_idx = [self.field_names.index(k) for k in self.join_keys]
        self._scan = table.new_read_builder().new_stream_scan()
        self._read = table.new_read_builder().new_read()
        self.refresh()

    # ---- load / refresh -------------------------------------------------
    def refresh(self) -> int:
        """Drain pending snapshots from the streaming scan (reference:
        FullCacheLookupTable.refresh polls the stream for new snapshots).
        Returns the number of change rows applied."""
        applied = 0
        while True:
            splits = self._scan.plan()
            if not splits:
                return applied
            for split in splits:
                rows, kinds = self._read_changes(split)
                for row, kind in zip(rows, kinds):
                    self._apply(row, kind)
                    applied += 1

    def _read_changes(self, split):
        """Rows + kinds of one split at the KeyValue level: -D rows must
        SURVIVE the read so the cache can retract them (the reference's
        LookupStreamingReader reads deltas unmerged for the same reason)."""
        if getattr(split, "is_changelog", False):
            data, kinds = self._read.read_with_kinds(split)
            return data.to_pylist(), kinds.tolist()
        from ..core.read import MergeFileSplitRead

        store = self.table.store
        read = MergeFileSplitRead(
            store.reader_factory(split.partition, split.bucket),
            store.merge_executor(),
            store.key_names,
        )
        kv = read.read_kv(split.files, drop_delete=False)
        return kv.data.to_pylist(), kv.kind.tolist()

    def _apply(self, row: tuple, kind: int) -> None:
        add = kind in (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER))
        jk = tuple(row[i] for i in self._jk_idx)
        if self.mode == "no-pk":
            if add:
                self._multi.setdefault(jk, []).append(row)
            else:
                rows = self._multi.get(jk)
                if rows and row in rows:
                    rows.remove(row)
            return
        pk = tuple(row[i] for i in self._pk_idx)
        if self.mode == "secondary":
            old = self._rows.get(pk)
            if old is not None:
                old_jk = tuple(old[i] for i in self._jk_idx)
                s = self._index.get(old_jk)
                if s is not None:
                    s.discard(pk)
        if add:
            self._rows[pk] = row
            if self.mode == "secondary":
                self._index.setdefault(jk, set()).add(pk)
        else:
            self._rows.pop(pk, None)

    # ---- lookup ---------------------------------------------------------
    def get(self, key: tuple | Sequence) -> list[tuple]:
        """Rows whose join key equals `key` (a tuple aligned with join_keys)."""
        key = tuple(key)
        if self.mode == "no-pk":
            return list(self._multi.get(key, ()))
        if self.mode == "primary":
            row = self._rows.get(key)
            return [row] if row is not None else []
        pks = self._index.get(key, ())
        return [self._rows[pk] for pk in sorted(pks) if pk in self._rows]

    def __len__(self) -> int:
        if self.mode == "no-pk":
            return sum(len(v) for v in self._multi.values())
        return len(self._rows)
