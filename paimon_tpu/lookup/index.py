"""Vectorized probe indexes for batched primary-key gets.

The batched twin of LookupLevels (lookup/__init__.py): where the scalar path
pays one python probe per key per file, this layer encodes a whole probe
batch ONCE through the JoinIndex machinery (ops/join.py: key lanes → global
LanePlan → truncate/pack → <= 64-bit fold) and pays one vectorized
searchsorted per surviving sorted run. Files are pruned BEFORE any data IO
by two zero-IO tests — the key range recorded in the manifest entry and the
PTIX composite key bloom (format/fileindex.py, written at flush/compaction
when file-index.bloom-filter.primary-key.enabled) — then surviving files'
decoded KVBatches come from the process-wide data-file cache (utils.cache),
so a sustained get workload decodes each immutable file exactly once.
Code-domain columns (merge.dict-domain) are probed on their dictionary
codes: the build side of the index never materializes a string.

Level resolution happens on the caller's side (table/get.py): every file's
matches carry (sequence, kind), the winner per key is the max-sequence row,
deletes mask to absent — the same merge rule the scalar LookupLevels walk
applies file-by-file, applied once over the whole batch.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import Sequence

import numpy as np

from ..core.datafile import DataFileMeta, KeyValueFileReaderFactory
from ..core.kv import KVBatch
from ..metrics import get_metrics

__all__ = ["FileProbeIndex", "BucketGetIndex", "GetResult"]


class GetResult:
    """Batched get outcome aligned with the probe keys: `found[i]` says key i
    resolved to a live row; `rows` holds exactly the found rows (in probe
    order) and `take[j]` is the probe index of rows[j]."""

    def __init__(self, n: int, found: np.ndarray, rows, take: np.ndarray):
        self.n = n
        self.found = found
        self.rows = rows  # ColumnBatch over the table's value schema
        self.take = take  # (found.sum(),) int64 probe indices, ascending

    def to_pylist(self) -> list:
        """list[tuple | None], one entry per probe key — the exact shape of
        a scalar lookup() loop (the parity oracle's contract)."""
        out: list = [None] * self.n
        vals = self.rows.to_pylist()
        for j, i in enumerate(self.take):
            out[int(i)] = vals[j]
        return out

    def row(self, i: int):
        """Row for probe key i as a tuple, or None."""
        if not self.found[i]:
            return None
        j = int(np.searchsorted(self.take, i))
        return tuple(c.value_at(j) for c in self.rows.columns.values())


class FileProbeIndex:
    """One data file (or one memtable generation), indexed for batch probes:
    a JoinIndex over the key columns plus the row-aligned (seq, kind)
    system vectors the level resolution needs."""

    def __init__(self, kv: KVBatch, key_names: Sequence[str]):
        from ..ops.join import JoinIndex

        self.kv = kv
        self.key_names = list(key_names)
        self.index = JoinIndex(kv.data, self.key_names)

    def probe(self, probe_batch) -> tuple[np.ndarray, np.ndarray]:
        """(probe_idx, row) pairs for every key match in this file."""
        if self.kv.num_rows == 0 or probe_batch.num_rows == 0:
            return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
        res = self.index.probe(probe_batch, self.key_names, how="inner")
        return np.asarray(res.left_take, dtype=np.int64), np.asarray(res.right_take, dtype=np.int64)


class BucketGetIndex:
    """One bucket's files, served for batched gets: zero-IO pruning (key
    range + bloom key index), lazily-built per-file probe indexes, matches
    returned with their resolution metadata. Instances are immutable views
    of one snapshot's file set — LocalTableQuery.refresh() diffs per bucket
    and keeps instances whose (files, deletion vectors) are unchanged, so
    built indexes survive snapshot advances that didn't touch the bucket."""

    def __init__(
        self,
        files: list[DataFileMeta],
        reader_factory: KeyValueFileReaderFactory,
        key_names: Sequence[str],
        deletion_vectors: dict | None = None,
        bloom_prune: bool = True,
        warm_from: "BucketGetIndex | None" = None,
    ):
        self.files = list(files)
        self.reader_factory = reader_factory
        self.key_names = list(key_names)
        self.deletion_vectors = deletion_vectors or {}
        self.bloom_prune = bloom_prune
        self._indexes: dict[str, FileProbeIndex] = {}
        self._payloads: dict[str, object] = {}  # file -> FileIndexPredicate|None
        if warm_from is not None:
            # carry warm state for files that persist across the snapshot
            # advance: an ordinary L0 append changes one file in the bucket,
            # and without the carry every built probe index is discarded and
            # the next get re-reads the whole bucket. Probe indexes bake in
            # deletion vectors, so a file is carried only when neither side
            # has a DV for it; PTIX predicates are DV-independent.
            names = {f.file_name for f in self.files}
            for name, idx in warm_from._indexes.items():
                if (
                    name in names
                    and name not in self.deletion_vectors
                    and name not in warm_from.deletion_vectors
                ):
                    self._indexes[name] = idx
            for name, pred in warm_from._payloads.items():
                if name in names:
                    self._payloads[name] = pred

    def prewarm(self) -> None:
        """Eagerly build the probe index for every file not already warm.
        Servers call this off the serving path (the follower refresh builds
        staged state outside the serving lock) so a snapshot advance never
        makes the first unlucky get pay the whole bucket's read cost."""
        for meta in self.files:
            if meta.file_name not in self._indexes:
                self._file_index(meta)

    # ---- pruning (no data IO) ------------------------------------------
    def _index_predicate(self, meta: DataFileMeta):
        """The file's PTIX index (embedded bytes or the small sidecar read),
        parsed once; None when the file carries no index."""
        name = meta.file_name
        if name not in self._payloads:
            from ..format.fileindex import FileIndexPredicate, index_path

            pred = None
            try:
                if meta.embedded_index is not None:
                    pred = FileIndexPredicate.from_bytes(meta.embedded_index)
                elif any(x.endswith(".index") for x in meta.extra_files):
                    data_path = f"{self.reader_factory.bucket_dir}/{name}"
                    pred = FileIndexPredicate(self.reader_factory.file_io, index_path(data_path))
            except (OSError, AssertionError, ValueError):
                pred = None  # a torn/missing sidecar never fails a get
            self._payloads[name] = pred
        return self._payloads[name]

    def _pruned(self, meta: DataFileMeta, hashes: np.ndarray, sorted_keys: list | None) -> bool:
        g = get_metrics()
        if sorted_keys and meta.min_key and meta.max_key:
            i = bisect_left(sorted_keys, tuple(meta.min_key))
            if i == len(sorted_keys) or sorted_keys[i] > tuple(meta.max_key):
                return True  # no probe key inside the file's key range
        if not self.bloom_prune:
            return False
        pred = self._index_predicate(meta)
        if pred is None:
            return False
        mask = pred.test_key_hashes(hashes)
        if mask is None:
            return False  # pre-key-index file: cannot prune by bloom
        g.counter("index_hits").inc()
        return not bool(mask.any())

    # ---- probing --------------------------------------------------------
    def _file_index(self, meta: DataFileMeta) -> FileProbeIndex:
        name = meta.file_name
        idx = self._indexes.get(name)
        if idx is None:
            kv = self.reader_factory.read(meta)
            dv = self.deletion_vectors.get(name)
            if dv is not None:
                keep = ~dv.deleted_mask(kv.num_rows)
                if not keep.all():
                    kv = kv.filter(keep)
            idx = self._indexes[name] = FileProbeIndex(kv, self.key_names)
        return idx

    def probe(self, probe_batch, hashes: np.ndarray, sorted_keys: list | None = None):
        """[(FileProbeIndex, probe_idx, rows)] across surviving files.
        `hashes`: the probe keys' combined uint64 hashes (computed once per
        get_batch, shared with bucket routing); `sorted_keys`: the probe key
        tuples sorted ascending (computed once, shared across buckets)."""
        g = get_metrics()
        out = []
        for meta in self.files:
            if self._pruned(meta, hashes, sorted_keys):
                g.counter("files_pruned").inc()
                continue
            fi = self._file_index(meta)
            g.counter("keys_probed").inc(probe_batch.num_rows)
            pi, rows = fi.probe(probe_batch)
            if len(pi):
                out.append((fi, pi, rows))
        return out
