"""KeyValueFileStore: the facade wiring scan/read/write/commit together.

Parity: /root/reference/paimon-core/.../FileStore.java:53 (newScan/newRead/
newWrite/newCommit) and KeyValueFileStore.java:62 (+ KeyValueFileStoreWrite.
createWriter :165-219 wiring memtable + compaction, restore from the latest
snapshot). Directory layout mirrors the reference:
  table/schema/schema-N
  table/snapshot/snapshot-N (+ LATEST/EARLIEST hints)
  table/manifest/{manifest-*,manifest-list-*}
  table/[k1=v1/k2=v2/]bucket-B/data-*.parquet
"""

from __future__ import annotations

from typing import Sequence

from ..fs import FileIO
from ..options import CoreOptions
from ..types import RowType
from ..utils import partition_path
from .commit import FileStoreCommit
from .compact import MergeTreeCompactManager, MergeTreeCompactRewriter, UniversalCompaction
from .datafile import DataFileMeta, KeyValueFileReaderFactory, KeyValueFileWriterFactory
from .expire import SnapshotExpire
from .levels import Levels
from .mergefn import MergeExecutor
from .read import MergeFileSplitRead
from .scan import FileStoreScan
from .schema import SchemaManager, TableSchema
from .snapshot import SnapshotManager
from .writer import MergeTreeWriter

__all__ = ["KeyValueFileStore"]


def _parse_per_level(spec: str | None) -> dict[int, str]:
    """'0:avro,5:parquet' -> {0: 'avro', 5: 'parquet'} (reference
    CoreOptions.fileFormatPerLevel / fileCompressionPerLevel)."""
    if not spec:
        return {}
    out: dict[int, str] = {}
    for part in spec.split(","):
        lvl, _, val = part.strip().partition(":")
        if not val:
            raise ValueError(f"per-level spec needs 'level:value' pairs, got {part!r}")
        out[int(lvl)] = val.strip()
    return out


def _resolve_key_bloom(co: CoreOptions) -> bool:
    from ..format.fileindex import resolve_key_bloom

    return resolve_key_bloom(co.options.get(CoreOptions.FILE_INDEX_BLOOM_KEY_ENABLED))


class KeyValueFileStore:
    def __init__(self, file_io: FileIO, table_path: str, schema: TableSchema, commit_user: str = "anonymous"):
        self.table_path = table_path
        self.schema = schema
        self.commit_user = commit_user
        self.options = schema.core_options()
        # resilience layer: every store-level path (scan / merge read /
        # commit / compact / expire) routes its IO through the retrying
        # wrapper, governed by fs.retry.* / fs.io.timeout; with retries
        # disabled the original FileIO is used unwrapped (zero indirection)
        from ..resilience import wrap_file_io

        self.file_io = wrap_file_io(file_io, self.options)
        self.value_schema: RowType = RowType(schema.fields)
        self.key_names = schema.trimmed_primary_keys
        self.partition_keys = list(schema.partition_keys)
        self.schema_manager = SchemaManager(self.file_io, table_path)
        # byte-budget caches (utils.cache): process-wide, shared by scan /
        # read / commit / compaction / lookup through this store's accessors;
        # None when the table opted out via a 0 budget
        from ..utils.cache import table_caches

        self.manifest_obj_cache, self.data_file_obj_cache = table_caches(self.options)
        self.snapshot_manager = SnapshotManager(self.file_io, table_path, cache=self.manifest_obj_cache)
        self._schemas_cache: dict[int, RowType] = {}

    # ---- layout --------------------------------------------------------
    def bucket_dir(self, partition: tuple, bucket: int) -> str:
        pp = partition_path(
            self.partition_keys,
            partition,
            default_name=self.options.options.get(CoreOptions.PARTITION_DEFAULT_NAME),
        )
        base = f"{self.table_path}/{pp}" if pp else self.table_path
        return f"{base}/bucket-{bucket}"

    def schemas_by_id(self) -> dict[int, RowType]:
        for sid, ts in self.schema_manager.all_schemas().items():
            if sid not in self._schemas_cache:
                self._schemas_cache[sid] = RowType(ts.fields)
        if self.schema.id not in self._schemas_cache:
            self._schemas_cache[self.schema.id] = self.value_schema
        return self._schemas_cache

    # ---- components ----------------------------------------------------
    def merge_executor(self) -> MergeExecutor:
        return MergeExecutor(self.value_schema, self.key_names, self.options.merge_engine, self.options)

    keyed = True

    def writer_factory(self, partition: tuple, bucket: int) -> KeyValueFileWriterFactory:
        co = self.options
        bloom_cols = co.options.get(CoreOptions.FILE_INDEX_BLOOM_COLUMNS)
        format_options = {
            k: v
            for k, v in co.options._data.items()
            if k.startswith(("format.", "orc.", "parquet.", "avro."))
        }
        # generic writer knobs the format backends understand
        block = co.options.get(CoreOptions.FILE_BLOCK_SIZE)
        if block is not None:
            format_options.setdefault("file.block-size", int(block))
        format_options.setdefault(
            "file.compression.zstd-level", co.options.get(CoreOptions.FILE_COMPRESSION_ZSTD_LEVEL)
        )
        # encoder selection (format.parquet.encoder = arrow | native); this
        # one seam routes memtable flush, compaction rewrite, changelog and
        # sort-compact writes through the chosen encode backend
        format_options.setdefault(
            "format.parquet.encoder", co.options.get(CoreOptions.FORMAT_PARQUET_ENCODER)
        )
        return KeyValueFileWriterFactory(
            self.file_io,
            self.bucket_dir(partition, bucket),
            self.value_schema,
            self.key_names,
            self.schema.id,
            file_format=co.file_format,
            compression=co.file_compression,
            target_file_size=co.target_file_size,
            bloom_columns=[c.strip() for c in bloom_cols.split(",")] if bloom_cols else (),
            bloom_fpp=co.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
            key_bloom=_resolve_key_bloom(co),
            key_bloom_fpp=co.options.get(CoreOptions.FILE_INDEX_BLOOM_KEY_FPP),
            index_in_manifest_threshold=int(
                co.options.get(CoreOptions.FILE_INDEX_IN_MANIFEST_THRESHOLD)
            ),
            keyed=self.keyed,
            format_options=format_options,
            include_key_columns=co.options.get(CoreOptions.DATA_FILE_INCLUDE_KEY_COLUMNS),
            per_level_format=_parse_per_level(co.options.get(CoreOptions.FILE_FORMAT_PER_LEVEL)),
            per_level_compression=_parse_per_level(co.options.get(CoreOptions.FILE_COMPRESSION_PER_LEVEL)),
        )

    def reader_factory(self, partition: tuple, bucket: int, read_schema: RowType | None = None) -> KeyValueFileReaderFactory:
        co = self.options
        # reader-side format options: raw format-scoped keys plus the
        # decoder selection (format.parquet.decoder = arrow | native); this
        # one seam routes core/read, compaction rewrites, sort_compact,
        # lookup and table reads through the chosen decode backend
        format_options = {
            k: v
            for k, v in co.options._data.items()
            if k.startswith(("format.", "orc.", "parquet.", "avro."))
        }
        format_options.setdefault(
            "format.parquet.decoder", co.options.get(CoreOptions.FORMAT_PARQUET_DECODER)
        )
        # compressed-domain merge (merge.dict-domain): readers return
        # dictionary codes for dict-encoded string chunks instead of
        # expanding them — one seam for merge read, compaction, sort-compact
        format_options.setdefault("merge.dict-domain", co.dict_domain)
        format_options.setdefault("merge.dict-domain.pool-limit", co.dict_domain_pool_limit)
        return KeyValueFileReaderFactory(
            self.file_io,
            self.bucket_dir(partition, bucket),
            read_schema or self.value_schema,
            self.schemas_by_id(),
            file_format=co.file_format,
            keyed=self.keyed,
            cache=self.data_file_obj_cache,
            format_options=format_options,
        )

    def pipeline_config(self) -> tuple[int, int | None]:
        """(scan.prefetch-splits, scan.parallelism) — the pipelined split
        scheduler's knobs (parallel/pipeline.py), resolved once here so
        read/compact/flush consumers all agree."""
        from ..parallel.pipeline import pipeline_config

        return pipeline_config(self.options)

    def new_scan(self) -> FileStoreScan:
        manifest_par = self.options.options.get(CoreOptions.SCAN_MANIFEST_PARALLELISM)
        if manifest_par is None:
            # scan.parallelism is the general pipeline knob; the manifest-
            # specific option stays the override
            manifest_par = self.options.options.get(CoreOptions.SCAN_PARALLELISM)
        return FileStoreScan(
            self.file_io,
            self.table_path,
            self.key_names,
            manifest_parallelism=manifest_par,
            cache=self.manifest_obj_cache,
        )

    def new_commit(self) -> FileStoreCommit:
        return FileStoreCommit(
            self.file_io,
            self.table_path,
            self.commit_user,
            self.schema.id,
            self.options,
            cache=self.manifest_obj_cache,
        )

    def new_expire(self, protected_ids=None) -> SnapshotExpire:
        return SnapshotExpire(
            self.file_io, self.table_path, self.options, protected_ids, partition_keys=self.partition_keys
        )

    # ---- write ---------------------------------------------------------
    def restore_files(self, partition: tuple, bucket: int) -> list[DataFileMeta]:
        plan = self.new_scan().with_bucket(bucket).with_partition_filter(lambda p: p == partition).plan()
        return [e.file for e in plan.entries]

    def restore_state(self, partition: tuple, bucket: int):
        """(files, deletion_vectors) for one bucket from the latest snapshot."""
        plan = self.new_scan().with_bucket(bucket).with_partition_filter(lambda p: p == partition).plan()
        files = [e.file for e in plan.entries]
        dvs: dict = {}
        dv_index = plan.dv_index_for(partition, bucket)
        if dv_index:
            from .deletionvectors import DeletionVectorsIndexFile

            dvs = DeletionVectorsIndexFile(self.file_io, self.table_path).read_all(dv_index)
        return files, dvs

    def new_writer(
        self,
        partition: tuple,
        bucket: int,
        total_buckets: int | None = None,
        restore: bool = True,
        admission=None,
    ) -> MergeTreeWriter:
        from ..options import ChangelogProducer

        if self.options.write_only and self.options.changelog_producer == ChangelogProducer.LOOKUP:
            raise ValueError(
                "changelog-producer=lookup needs the writer's levels view and cannot run with "
                "write-only=true (produce the changelog in the writing job, not a dedicated compactor)"
            )
        existing, dvs = self.restore_state(partition, bucket) if restore else ([], {})
        max_seq = max((f.max_sequence_number for f in existing), default=-1)
        levels = Levels(existing, self.options.num_levels)
        merge = self.merge_executor()
        wf = self.writer_factory(partition, bucket)
        compact_manager = None
        if not self.options.write_only:
            strategy = UniversalCompaction(
                self.options.max_size_amplification_percent,
                self.options.size_ratio,
                self.options.num_sorted_runs_compaction_trigger,
                self.options.options.get(CoreOptions.COMPACTION_OPTIMIZATION_INTERVAL),
                max_file_num=self.options.options.get(CoreOptions.COMPACTION_MAX_FILE_NUM),
            )
            from ..options import ChangelogProducer

            rewriter = MergeTreeCompactRewriter(
                self.reader_factory(partition, bucket),
                wf,
                merge,
                deletion_vectors=dvs,
                emit_full_changelog=(
                    self.options.changelog_producer == ChangelogProducer.FULL_COMPACTION
                    or (
                        # lookup producer with lookup-wait=false: changelog
                        # production deferred to compaction (writer skips it)
                        self.options.changelog_producer == ChangelogProducer.LOOKUP
                        and not self.options.options.get(
                            CoreOptions.CHANGELOG_PRODUCER_LOOKUP_WAIT
                        )
                    )
                ),
                row_deduplicate=self.options.options.get(CoreOptions.CHANGELOG_PRODUCER_ROW_DEDUPLICATE),
                expire_predicate=self.record_expire_predicate(),
            )
            compact_manager = MergeTreeCompactManager(levels, strategy, rewriter, self.options)
        debt_gate = None
        if self.options.write_only and self.options.options.get(
            CoreOptions.COMPACTION_ADAPTIVE_INGEST_GATE
        ):
            # write-only ingest has no inline compaction manager bounding its
            # sorted runs: resolve the adaptive scheduler's debt-admission
            # gate lazily per flush, so a service started AFTER this writer
            # still bounds it (ISSUE 12, PR 11 follow-up)
            import functools

            from ..table.compactor import active_debt_gate

            debt_gate = functools.partial(active_debt_gate, self.table_path)
        return MergeTreeWriter(
            partition,
            bucket,
            total_buckets if total_buckets is not None else max(self.options.bucket, 1),
            wf,
            merge,
            compact_manager,
            self.options,
            restored_max_seq=max_seq,
            admission=admission,
            debt_gate=debt_gate,
        )

    # ---- read ----------------------------------------------------------
    def record_expire_predicate(self):
        """Row TTL (reference io/RecordLevelExpire): rows whose time field is
        older than record-level.expire-time.ms are dropped on read and during
        compaction rewrites. The column unit comes from
        record-level.time-field-type (seconds | millis | micros)."""
        ttl = self.options.options.get(CoreOptions.RECORD_LEVEL_EXPIRE_TIME_MS)
        field = self.options.options.get(CoreOptions.RECORD_LEVEL_TIME_FIELD)
        if ttl is None or field is None:
            return None
        from ..data.predicate import greater_than, is_null, or_
        from ..utils import now_millis

        unit = self.options.options.get(CoreOptions.RECORD_LEVEL_TIME_FIELD_TYPE)
        cutoff_ms = now_millis() - ttl
        scale = {"seconds": 1000, "millis": 1, "micros": None}.get(unit, 1000)
        cutoff = cutoff_ms * 1000 if scale is None else cutoff_ms // scale
        # rows with a NULL time field are KEPT, never silently expired: the
        # reference's contract is that the field must be non-null
        # (RecordLevelExpire.java:86-87 checkArgument) — eval would collapse
        # NULL > cutoff to False and permanently drop the row otherwise
        return or_(greater_than(field, cutoff), is_null(field))

    def read_bucket(
        self,
        partition: tuple,
        bucket: int,
        files: list[DataFileMeta],
        predicate=None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
        deletion_vectors: dict | None = None,
    ):
        return self.read_bucket_dispatch(
            partition, bucket, files, predicate, projection, drop_delete, deletion_vectors
        )()

    def read_bucket_dispatch(
        self,
        partition: tuple,
        bucket: int,
        files: list[DataFileMeta],
        predicate=None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
        deletion_vectors: dict | None = None,
    ):
        """Two-phase read_bucket for mesh execution: returns a continuation;
        the merge jobs of all buckets dispatched in one MeshBatchContext run
        in a single batched shard_map."""
        expire = self.record_expire_predicate()
        if expire is not None:
            from ..data.predicate import and_

            predicate = expire if predicate is None else and_(predicate, expire)
        read = MergeFileSplitRead(
            self.reader_factory(partition, bucket),
            self.merge_executor(),
            self.key_names,
            parallelism=self.options.options.get(CoreOptions.SCAN_PARALLELISM),
        )
        return read.read_split_dispatch(files, predicate, projection, drop_delete, deletion_vectors)


class AppendOnlyFileStore(KeyValueFileStore):
    """No-PK store: plain rows, concat reads, small-file compaction
    (reference AppendOnlyFileStore.java:44)."""

    keyed = False

    def new_writer(
        self,
        partition: tuple,
        bucket: int,
        total_buckets: int | None = None,
        restore: bool = True,
        admission=None,  # accepted for signature parity; the append writer
        # buffers through its own spillable path and takes no byte admission
    ):
        from .append import AppendOnlyCompactManager, AppendOnlyWriter

        existing = self.restore_files(partition, bucket) if restore else []
        max_seq = max((f.max_sequence_number for f in existing), default=-1)
        wf = self.writer_factory(partition, bucket)
        compact_manager = None
        if not self.options.write_only:
            compact_manager = AppendOnlyCompactManager(self.reader_factory(partition, bucket), wf, self.options)
        return AppendOnlyWriter(
            partition,
            bucket,
            total_buckets if total_buckets is not None else max(self.options.bucket, 1),
            wf,
            compact_manager,
            self.options,
            existing_files=existing,
            restored_max_seq=max_seq,
        )

    def read_bucket(
        self,
        partition: tuple,
        bucket: int,
        files: list[DataFileMeta],
        predicate=None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
        deletion_vectors: dict | None = None,
    ):
        from ..data.batch import ColumnBatch, concat_batches

        dvs = deletion_vectors or {}
        rf = self.reader_factory(partition, bucket)
        ordered = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
        out = []
        for f in ordered:
            dv = dvs.get(f.file_name)
            kv = rf.read(f, predicate=None if dv is not None else predicate)
            if dv is not None:
                mask = ~dv.deleted_mask(kv.num_rows)
                if not mask.all():
                    kv = kv.filter(mask)
            data = kv.data
            if predicate is not None and data.num_rows:
                mask = predicate.eval(data)
                if not mask.all():
                    data = data.filter(mask)
            if projection is not None:
                data = data.select(projection)
            out.append(data)
        if not out:
            schema = self.value_schema if projection is None else self.value_schema.project(projection)
            return ColumnBatch.empty(schema)
        return concat_batches(out)

    def read_bucket_dispatch(self, *args, **kwargs):
        """Append reads have no merge to batch: the continuation just wraps
        the eager concat read."""
        out = self.read_bucket(*args, **kwargs)
        return lambda: out
