"""KeyValueFileStore: the facade wiring scan/read/write/commit together.

Parity: /root/reference/paimon-core/.../FileStore.java:53 (newScan/newRead/
newWrite/newCommit) and KeyValueFileStore.java:62 (+ KeyValueFileStoreWrite.
createWriter :165-219 wiring memtable + compaction, restore from the latest
snapshot). Directory layout mirrors the reference:
  table/schema/schema-N
  table/snapshot/snapshot-N (+ LATEST/EARLIEST hints)
  table/manifest/{manifest-*,manifest-list-*}
  table/[k1=v1/k2=v2/]bucket-B/data-*.parquet
"""

from __future__ import annotations

from typing import Sequence

from ..fs import FileIO
from ..options import CoreOptions
from ..types import RowType
from ..utils import partition_path
from .commit import FileStoreCommit
from .compact import MergeTreeCompactManager, MergeTreeCompactRewriter, UniversalCompaction
from .datafile import DataFileMeta, KeyValueFileReaderFactory, KeyValueFileWriterFactory
from .expire import SnapshotExpire
from .levels import Levels
from .mergefn import MergeExecutor
from .read import MergeFileSplitRead
from .scan import FileStoreScan
from .schema import SchemaManager, TableSchema
from .snapshot import SnapshotManager
from .writer import MergeTreeWriter

__all__ = ["KeyValueFileStore"]


class KeyValueFileStore:
    def __init__(self, file_io: FileIO, table_path: str, schema: TableSchema, commit_user: str = "anonymous"):
        self.file_io = file_io
        self.table_path = table_path
        self.schema = schema
        self.commit_user = commit_user
        self.options = schema.core_options()
        self.value_schema: RowType = RowType(schema.fields)
        self.key_names = schema.trimmed_primary_keys
        self.partition_keys = list(schema.partition_keys)
        self.schema_manager = SchemaManager(file_io, table_path)
        self.snapshot_manager = SnapshotManager(file_io, table_path)
        self._schemas_cache: dict[int, RowType] = {}

    # ---- layout --------------------------------------------------------
    def bucket_dir(self, partition: tuple, bucket: int) -> str:
        pp = partition_path(self.partition_keys, partition)
        base = f"{self.table_path}/{pp}" if pp else self.table_path
        return f"{base}/bucket-{bucket}"

    def schemas_by_id(self) -> dict[int, RowType]:
        for sid, ts in self.schema_manager.all_schemas().items():
            if sid not in self._schemas_cache:
                self._schemas_cache[sid] = RowType(ts.fields)
        if self.schema.id not in self._schemas_cache:
            self._schemas_cache[self.schema.id] = self.value_schema
        return self._schemas_cache

    # ---- components ----------------------------------------------------
    def merge_executor(self) -> MergeExecutor:
        return MergeExecutor(self.value_schema, self.key_names, self.options.merge_engine, self.options)

    def writer_factory(self, partition: tuple, bucket: int) -> KeyValueFileWriterFactory:
        co = self.options
        bloom_cols = co.options.get(CoreOptions.FILE_INDEX_BLOOM_COLUMNS)
        return KeyValueFileWriterFactory(
            self.file_io,
            self.bucket_dir(partition, bucket),
            self.value_schema,
            self.key_names,
            self.schema.id,
            file_format=co.file_format,
            compression=co.file_compression,
            target_file_size=co.target_file_size,
            bloom_columns=[c.strip() for c in bloom_cols.split(",")] if bloom_cols else (),
            bloom_fpp=co.options.get(CoreOptions.FILE_INDEX_BLOOM_FPP),
        )

    def reader_factory(self, partition: tuple, bucket: int, read_schema: RowType | None = None) -> KeyValueFileReaderFactory:
        return KeyValueFileReaderFactory(
            self.file_io,
            self.bucket_dir(partition, bucket),
            read_schema or self.value_schema,
            self.schemas_by_id(),
            file_format=self.options.file_format,
        )

    def new_scan(self) -> FileStoreScan:
        return FileStoreScan(self.file_io, self.table_path, self.key_names)

    def new_commit(self) -> FileStoreCommit:
        return FileStoreCommit(
            self.file_io, self.table_path, self.commit_user, self.schema.id, self.options
        )

    def new_expire(self, protected_ids=None) -> SnapshotExpire:
        return SnapshotExpire(
            self.file_io, self.table_path, self.options, protected_ids, partition_keys=self.partition_keys
        )

    # ---- write ---------------------------------------------------------
    def restore_files(self, partition: tuple, bucket: int) -> list[DataFileMeta]:
        plan = self.new_scan().with_bucket(bucket).with_partition_filter(lambda p: p == partition).plan()
        return [e.file for e in plan.entries]

    def new_writer(self, partition: tuple, bucket: int, total_buckets: int | None = None, restore: bool = True) -> MergeTreeWriter:
        existing = self.restore_files(partition, bucket) if restore else []
        max_seq = max((f.max_sequence_number for f in existing), default=-1)
        levels = Levels(existing, self.options.num_levels)
        merge = self.merge_executor()
        wf = self.writer_factory(partition, bucket)
        compact_manager = None
        if not self.options.write_only:
            strategy = UniversalCompaction(
                self.options.max_size_amplification_percent,
                self.options.size_ratio,
                self.options.num_sorted_runs_compaction_trigger,
                self.options.options.get(CoreOptions.COMPACTION_OPTIMIZATION_INTERVAL),
            )
            rewriter = MergeTreeCompactRewriter(self.reader_factory(partition, bucket), wf, merge)
            compact_manager = MergeTreeCompactManager(levels, strategy, rewriter, self.options)
        return MergeTreeWriter(
            partition,
            bucket,
            total_buckets if total_buckets is not None else max(self.options.bucket, 1),
            wf,
            merge,
            compact_manager,
            self.options,
            restored_max_seq=max_seq,
        )

    # ---- read ----------------------------------------------------------
    def read_bucket(
        self,
        partition: tuple,
        bucket: int,
        files: list[DataFileMeta],
        predicate=None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
    ):
        read = MergeFileSplitRead(self.reader_factory(partition, bucket), self.merge_executor(), self.key_names)
        return read.read_split(files, predicate, projection, drop_delete)
