"""Table schemas: versioned, field-id based, with evolution.

Parity: /root/reference/paimon-core/.../schema/ — TableSchema (versioned JSON
with fields/ids, partition keys, primary keys, options), SchemaManager.java:76
(commitChanges with optimistic CAS rename), SchemaChange ops (add/drop/rename/
update column, set/remove option), SchemaValidation, SchemaEvolutionUtil.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Sequence

from ..fs import FileIO
from ..options import CoreOptions, Options
from ..types import DataField, DataType, RowType, parse_type
from ..utils import dumps, loads, now_millis
from ..data.casting import can_cast

__all__ = ["TableSchema", "SchemaManager", "SchemaChange"]


@dataclass(frozen=True)
class TableSchema:
    id: int
    fields: tuple[DataField, ...]
    highest_field_id: int
    partition_keys: tuple[str, ...]
    primary_keys: tuple[str, ...]
    options: dict[str, str]
    comment: str | None = None
    time_millis: int = 0

    @property
    def row_type(self) -> RowType:
        return RowType(self.fields, nullable=False)

    @property
    def trimmed_primary_keys(self) -> list[str]:
        """PK minus partition keys — the in-bucket merge key (reference
        TableSchema.trimmedPrimaryKeys: partition values are constant within
        a partition, so they don't discriminate)."""
        trimmed = [k for k in self.primary_keys if k not in self.partition_keys]
        return trimmed if trimmed else list(self.primary_keys)

    @property
    def bucket_keys(self) -> list[str]:
        opt = self.options.get("bucket-key")
        if opt:
            return [s.strip() for s in opt.split(",")]
        return self.trimmed_primary_keys if self.primary_keys else [f.name for f in self.fields]

    def core_options(self) -> CoreOptions:
        return CoreOptions(Options(dict(self.options)))

    def to_json(self) -> str:
        return dumps(
            {
                "version": 1,
                "id": self.id,
                "fields": [f.to_dict() for f in self.fields],
                "highestFieldId": self.highest_field_id,
                "partitionKeys": list(self.partition_keys),
                "primaryKeys": list(self.primary_keys),
                "options": self.options,
                "comment": self.comment,
                "timeMillis": self.time_millis,
            }
        )

    @staticmethod
    def from_json(s: str | bytes) -> "TableSchema":
        d = loads(s)
        return TableSchema(
            id=d["id"],
            fields=tuple(DataField.from_dict(f) for f in d["fields"]),
            highest_field_id=d["highestFieldId"],
            partition_keys=tuple(d["partitionKeys"]),
            primary_keys=tuple(d["primaryKeys"]),
            options=d["options"],
            comment=d.get("comment"),
            time_millis=d.get("timeMillis", 0),
        )


class SchemaChange:
    """Declarative evolution ops (reference schema/SchemaChange.java)."""

    @staticmethod
    def add_column(name: str, dtype: DataType, description: str | None = None) -> dict:
        return {"op": "add", "name": name, "type": dtype, "description": description}

    @staticmethod
    def drop_column(name: str) -> dict:
        return {"op": "drop", "name": name}

    @staticmethod
    def rename_column(name: str, new_name: str) -> dict:
        return {"op": "rename", "name": name, "newName": new_name}

    @staticmethod
    def update_column_type(name: str, dtype: DataType) -> dict:
        return {"op": "updateType", "name": name, "type": dtype}

    @staticmethod
    def set_option(key: str, value: str) -> dict:
        return {"op": "setOption", "key": key, "value": value}

    @staticmethod
    def remove_option(key: str) -> dict:
        return {"op": "removeOption", "key": key}


class SchemaManager:
    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.table_path = table_path
        self.schema_dir = f"{table_path}/schema"
        # schema-N files are immutable once written (evolution only ever
        # adds schema-(N+1)), so decoded schemas memoize per manager — the
        # read path resolves every data file's schema_id for evolution
        # mapping, and without this each read_all paid store RTTs re-reading
        # bytes that cannot have changed
        self._decoded: dict[int, TableSchema] = {}

    def schema_path(self, schema_id: int) -> str:
        return f"{self.schema_dir}/schema-{schema_id}"

    def schema(self, schema_id: int) -> TableSchema:
        out = self._decoded.get(schema_id)
        if out is None:
            out = TableSchema.from_json(self.file_io.read_bytes(self.schema_path(schema_id)))
            self._decoded[schema_id] = out
        return out

    def _listed_ids(self) -> list[int]:
        out = []
        for st in self.file_io.list_files(self.schema_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("schema-"):
                try:
                    out.append(int(base[len("schema-") :]))
                except ValueError:
                    pass
        return sorted(out)

    def latest(self) -> TableSchema | None:
        ids = self._listed_ids()
        return self.schema(ids[-1]) if ids else None

    def all_schemas(self) -> dict[int, TableSchema]:
        return {i: self.schema(i) for i in self._listed_ids()}

    # ---- creation & evolution ------------------------------------------
    def create_table(
        self,
        row_type: RowType,
        partition_keys: Sequence[str] = (),
        primary_keys: Sequence[str] = (),
        options: dict[str, str] | None = None,
        comment: str | None = None,
    ) -> TableSchema:
        existing = self.latest()
        if existing is not None:
            return existing
        self._validate(row_type, partition_keys, primary_keys, options)
        fields = []
        for i, f in enumerate(row_type.fields):
            t = f.type
            if f.name in primary_keys and t.nullable:
                t = t.with_nullable(False)  # primary keys are NOT NULL
            fields.append(DataField(i, f.name, t, f.description))
        schema = TableSchema(
            id=0,
            fields=tuple(fields),
            highest_field_id=len(fields) - 1,
            partition_keys=tuple(partition_keys),
            primary_keys=tuple(primary_keys),
            options=dict(options or {}),
            comment=comment,
            time_millis=now_millis(),
        )
        if not self.file_io.try_atomic_write(self.schema_path(0), schema.to_json().encode()):
            return self.latest()  # lost the race; adopt the winner
        return schema

    @staticmethod
    def _validate(
        row_type: RowType,
        partition_keys: Sequence[str],
        primary_keys: Sequence[str],
        options: dict | None = None,
    ) -> None:
        for k in list(partition_keys) + list(primary_keys):
            if k not in row_type:
                raise ValueError(f"key column {k!r} not in schema {row_type.field_names}")
        if primary_keys and partition_keys:
            missing = [p for p in partition_keys if p not in primary_keys]
            from ..options import CoreOptions

            cross_partition = CoreOptions(options or {}).bucket == -1
            if missing and not cross_partition:
                raise ValueError(
                    f"primary key must contain all partition keys (missing {missing}) "
                    f"unless bucket=-1 enables cross-partition upsert "
                    f"— same constraint as the reference SchemaValidation"
                )

    def commit_changes(self, *changes: dict) -> TableSchema:
        """Optimistic evolve-and-CAS loop (reference SchemaManager.commitChanges)."""
        while True:
            base = self.latest()
            if base is None:
                raise RuntimeError("no table schema to evolve")
            evolved = self._apply(base, changes)
            path = self.schema_path(evolved.id)
            if self.file_io.try_atomic_write(path, evolved.to_json().encode()):
                return evolved
            # lost a race: retry against the new latest

    def _apply(self, base: TableSchema, changes: Sequence[dict]) -> TableSchema:
        fields = list(base.fields)
        options = dict(base.options)
        highest = base.highest_field_id
        names = lambda: [f.name for f in fields]  # noqa: E731
        for ch in changes:
            op = ch["op"]
            if op == "add":
                if ch["name"] in names():
                    raise ValueError(f"column {ch['name']} exists")
                highest += 1
                fields.append(DataField(highest, ch["name"], ch["type"], ch.get("description")))
            elif op == "drop":
                if ch["name"] in base.primary_keys or ch["name"] in base.partition_keys:
                    raise ValueError(f"cannot drop key column {ch['name']}")
                fields = [f for f in fields if f.name != ch["name"]]
            elif op == "rename":
                if ch["name"] in base.primary_keys or ch["name"] in base.partition_keys:
                    raise ValueError(f"cannot rename key column {ch['name']}")
                if ch["newName"] in names():
                    raise ValueError(f"column {ch['newName']} exists")
                fields = [
                    replace(f, name=ch["newName"]) if f.name == ch["name"] else f for f in fields
                ]
            elif op == "updateType":
                def upd(f: DataField) -> DataField:
                    if f.name != ch["name"]:
                        return f
                    if not can_cast(f.type, ch["type"]):
                        raise ValueError(f"cannot evolve {f.type.root} -> {ch['type'].root}")
                    return replace(f, type=ch["type"])

                fields = [upd(f) for f in fields]
            elif op == "setOption":
                options[ch["key"]] = ch["value"]
            elif op == "removeOption":
                options.pop(ch["key"], None)
            else:
                raise ValueError(f"unknown schema change {op}")
        return TableSchema(
            id=base.id + 1,
            fields=tuple(fields),
            highest_field_id=highest,
            partition_keys=base.partition_keys,
            primary_keys=base.primary_keys,
            options=options,
            comment=base.comment,
            time_millis=now_millis(),
        )
