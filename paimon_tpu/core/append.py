"""Append-only (no primary key) tables: writer + small-file compaction.

Parity: /root/reference/paimon-core/.../append/ — AppendOnlyWriter.java:62
(direct row buffer, rolling files), AppendOnlyCompactManager (concatenate
consecutive small files until target size; no merge function — order is
preserved), AppendOnlyFileStoreTable.java:50. Bucket modes: fixed (hash of
bucket key) or unaware (bucket -1: one shared bucket-0 namespace, compaction
planned separately — reference AppendOnlyTableCompactionCoordinator).
"""

from __future__ import annotations

import numpy as np

from ..data.batch import ColumnBatch
from ..options import CoreOptions
from ..types import RowKind
from .datafile import DataFileMeta, KeyValueFileReaderFactory, KeyValueFileWriterFactory
from .kv import KVBatch
from .manifest import CommitMessage

__all__ = ["AppendOnlyWriter", "AppendOnlyCompactManager"]


class AppendOnlyCompactManager:
    """Pick consecutive small files and concatenate them (order-preserving)."""

    def __init__(
        self,
        reader_factory: KeyValueFileReaderFactory,
        writer_factory: KeyValueFileWriterFactory,
        options: CoreOptions,
        deletion_vectors: dict | None = None,
    ):
        self.reader_factory = reader_factory
        self.writer_factory = writer_factory
        self.options = options
        self.deletion_vectors = deletion_vectors or {}

    def pick(self, files: list[DataFileMeta], full: bool = False) -> list[DataFileMeta] | None:
        """Consecutive (in sequence order) run of small files whose total
        reaches the target size (reference AppendOnlyCompactManager#
        pickCompactBefore); full=True rewrites everything into target-size
        files."""
        files = sorted(files, key=lambda f: (f.min_sequence_number, f.file_name))
        if full:
            return files if len(files) > 1 else None
        target = self.options.target_file_size
        min_count = self.options.compaction_min_file_num
        small: list[DataFileMeta] = []
        for f in files:
            if f.file_size < target:
                small.append(f)
                if len(small) >= min_count or sum(x.file_size for x in small) >= target:
                    return small
            else:
                small = []
        return None

    def compact(self, files: list[DataFileMeta], full: bool = False) -> tuple[list[DataFileMeta], list[DataFileMeta]]:
        pick = self.pick(files, full)
        if not pick:
            return [], []
        out = concat_rewrite(self.reader_factory, self.writer_factory, pick, self.deletion_vectors)
        return pick, out


def concat_rewrite(
    reader_factory: KeyValueFileReaderFactory,
    writer_factory: KeyValueFileWriterFactory,
    files: list[DataFileMeta],
    deletion_vectors: dict | None = None,
) -> list[DataFileMeta]:
    """Order-preserving concat of small append files into rolled output (the
    shared worker body of AppendOnlyCompactManager and the dedicated
    coordinator/worker split)."""
    dvs = deletion_vectors or {}
    batches = []
    for f in files:
        kv = reader_factory.read(f)
        dv = dvs.get(f.file_name)
        if dv is not None:
            mask = ~dv.deleted_mask(kv.num_rows)
            if not mask.all():
                kv = kv.filter(mask)
        batches.append(kv)
    kv = KVBatch.concat(batches)
    # keyed=False readers surface no per-row seqs; re-derive an in-range
    # sequence span so ordering and writer restore stay correct
    base = min(f.min_sequence_number for f in files)
    kv = KVBatch(kv.data, np.arange(base, base + kv.num_rows, dtype=np.int64), kv.kind)
    out = writer_factory.write(kv, level=0, file_source="compact")
    # the concatenated inputs leave the live view: free their cache budget
    from ..utils.cache import invalidate_data_file

    for f in files:
        invalidate_data_file(f.file_name)
    return out


class AppendOnlyWriter:
    """Buffers row batches and rolls them into data files — no keys, no
    merge; sequence numbers order files for streaming reads."""

    def __init__(
        self,
        partition: tuple,
        bucket: int,
        total_buckets: int,
        writer_factory: KeyValueFileWriterFactory,
        compact_manager: AppendOnlyCompactManager | None,
        options: CoreOptions,
        existing_files: list[DataFileMeta] | None = None,
        restored_max_seq: int = -1,
    ):
        self.partition = partition
        self.bucket = bucket
        self.total_buckets = total_buckets
        self.writer_factory = writer_factory
        self.compact_manager = compact_manager
        self.options = options
        self.seq = restored_max_seq + 1
        self._existing = list(existing_files or [])
        self._buffer: list[ColumnBatch] = []
        self._buffered_rows = 0
        self._buffered_bytes = 0
        self._spill = None
        self._io_manager = None
        # write-buffer-for-append turns on the buffered+spillable append path
        # even without the generic write-buffer-spillable switch (reference:
        # append writers only use a write buffer when this is set)
        if options.options.get(CoreOptions.WRITE_BUFFER_SPILLABLE) or options.options.get(
            CoreOptions.WRITE_BUFFER_FOR_APPEND
        ):
            from .disk import IOManager, SpillableBuffer

            self._io_manager = IOManager()
            self._spill = SpillableBuffer(
                self._io_manager,
                in_memory_rows=options.options.get(CoreOptions.WRITE_BUFFER_SPILL_ROWS),
                in_memory_bytes=int(options.options.get(CoreOptions.WRITE_BUFFER_SPILL_SIZE)),
                max_disk_bytes=int(options.options.get(CoreOptions.WRITE_BUFFER_SPILL_MAX_DISK_SIZE)),
            )
        self._new_files: list[DataFileMeta] = []
        self._compact_before: list[DataFileMeta] = []
        self._compact_after: list[DataFileMeta] = []

    def write(self, data: ColumnBatch, kinds: np.ndarray | None = None) -> None:
        if kinds is not None and (np.asarray(kinds) != int(RowKind.INSERT)).any():
            raise ValueError("append-only tables accept only +I records")
        if data.num_rows == 0:
            return
        if self._spill is not None:
            self._spill.add(data)  # spills to local disk beyond the cap
            self._buffered_rows = self._spill.num_rows
        else:
            self._buffer.append(data)
            self._buffered_rows += data.num_rows
            self._buffered_bytes += data.byte_size()
        if (
            self._buffered_rows >= self.options.write_buffer_rows
            or self._buffered_bytes >= self.options.write_buffer_size
            or (self._spill is not None and self._spill.disk_full)
        ):
            self.flush()

    def flush(self) -> None:
        from ..data.batch import concat_batches

        wrote = False
        if self._spill is not None:
            # stream segments straight to files: peak memory stays at the
            # spill cap instead of re-materializing the whole buffer
            for segment in self._spill.batches():
                kv = KVBatch.from_rows(segment, self.seq)
                self.seq += segment.num_rows
                self._new_files.extend(self.writer_factory.write(kv, level=0, file_source="append"))
                wrote = True
            self._spill.clear()
        elif self._buffer:
            data = concat_batches(self._buffer) if len(self._buffer) > 1 else self._buffer[0]
            kv = KVBatch.from_rows(data, self.seq)
            self.seq += data.num_rows
            self._new_files.extend(self.writer_factory.write(kv, level=0, file_source="append"))
            wrote = True
        self._buffer.clear()
        self._buffered_rows = 0
        self._buffered_bytes = 0
        if wrote and self.compact_manager is not None and not self.options.write_only:
            self._maybe_compact()

    def _maybe_compact(self, full: bool = False) -> None:
        assert self.compact_manager is not None
        consumed = {f.file_name for f in self._compact_before}
        current = [f for f in self._existing if f.file_name not in consumed] + [
            f for f in self._new_files if f.file_name not in consumed
        ] + [f for f in self._compact_after if f.file_name not in consumed]
        before, after = self.compact_manager.compact(current, full=full)
        self._compact_before.extend(before)
        self._compact_after.extend(after)

    def compact(self, full: bool = False) -> None:
        self.flush()
        if self.compact_manager is not None:
            self._maybe_compact(full=full)

    # mesh-batch protocol shims: append writes have no device merge to batch
    def flush_dispatch(self):
        self.flush()
        return None

    def flush_complete(self, state) -> None:  # pragma: no cover - no-op
        pass

    def compact_dispatch(self, full: bool = False):
        if self.compact_manager is not None:
            self._maybe_compact(full=full)
        return None

    def compact_complete(self, state) -> None:
        pass

    def prepare_commit(self) -> CommitMessage:
        self.flush()
        # files created AND consumed by compaction within this commit cancel
        before_names = {f.file_name for f in self._compact_before}
        after_names = {f.file_name for f in self._compact_after}
        cancel = before_names & after_names
        before = [f for f in self._compact_before if f.file_name not in cancel]
        after = [f for f in self._compact_after if f.file_name not in cancel]
        msg = CommitMessage(
            partition=self.partition,
            bucket=self.bucket,
            total_buckets=self.total_buckets,
            new_files=list(self._new_files),
            compact_before=before,
            compact_after=after,
        )
        consumed = {f.file_name for f in before}
        self._existing = [f for f in self._existing if f.file_name not in consumed] + list(self._new_files) + after
        self._existing = [f for f in self._existing if f.file_name not in consumed]
        self._new_files.clear()
        self._compact_before.clear()
        self._compact_after.clear()
        return msg

    def close(self) -> None:
        if self._spill is not None:
            self._spill.clear()
        if self._io_manager is not None:
            self._io_manager.close()
