"""KeyValue batch model: rows + sequence numbers + row kinds, vectorized.

Parity: /root/reference/paimon-core/.../KeyValue.java:44 — a KeyValue is
(key, sequenceNumber, valueKind, value, level). Batch-wise that is one
ColumnBatch of the value row type plus two system vectors. The on-disk schema
is `_SEQUENCE_NUMBER BIGINT, _VALUE_KIND TINYINT, <value fields...>`
(KeyValue.java:115-120 puts key fields first; here the primary key is always a
subset of the value fields, so key columns are normally projected, not
duplicated (data-file.include-key-columns opts into the reference's
duplicated _KEY_ layout for byte-level interop) —
one less copy on the wire and on device).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch, concat_batches
from ..types import BIGINT, TINYINT, DataField, RowKind, RowType

__all__ = ["KVBatch", "SEQUENCE_FIELD_NAME", "VALUE_KIND_FIELD_NAME", "kv_disk_schema", "LEVEL_FIELD_ID_BASE"]

SEQUENCE_FIELD_NAME = "_SEQUENCE_NUMBER"
VALUE_KIND_FIELD_NAME = "_VALUE_KIND"
# system field ids sit far above user ids (reference SpecialFields uses max-int range)
LEVEL_FIELD_ID_BASE = 2147480000


def kv_disk_schema(value_schema: RowType) -> RowType:
    fields = [
        DataField(LEVEL_FIELD_ID_BASE + 1, SEQUENCE_FIELD_NAME, BIGINT(False)),
        DataField(LEVEL_FIELD_ID_BASE + 2, VALUE_KIND_FIELD_NAME, TINYINT(False)),
        *value_schema.fields,
    ]
    return RowType(fields)


@dataclass
class KVBatch:
    """A batch of KeyValues: data (value schema), seq (int64), kind (uint8)."""

    data: ColumnBatch
    seq: np.ndarray
    kind: np.ndarray

    def __post_init__(self):
        assert len(self.seq) == len(self.kind) == self.data.num_rows
        assert self.seq.dtype == np.int64 and self.kind.dtype == np.uint8

    @property
    def num_rows(self) -> int:
        return self.data.num_rows

    def byte_size(self) -> int:
        return self.data.byte_size() + self.seq.nbytes + self.kind.nbytes

    def take(self, indices: np.ndarray) -> "KVBatch":
        return KVBatch(self.data.take(indices), self.seq.take(indices), self.kind.take(indices))

    def filter(self, mask: np.ndarray) -> "KVBatch":
        return KVBatch(self.data.filter(mask), self.seq[mask], self.kind[mask])

    def slice(self, start: int, stop: int) -> "KVBatch":
        return KVBatch(self.data.slice(start, stop), self.seq[start:stop], self.kind[start:stop])

    @staticmethod
    def concat(batches: Sequence["KVBatch"]) -> "KVBatch":
        return KVBatch(
            concat_batches([b.data for b in batches]),
            np.concatenate([b.seq for b in batches]),
            np.concatenate([b.kind for b in batches]),
        )

    @staticmethod
    def from_rows(data: ColumnBatch, start_seq: int, kinds: np.ndarray | None = None) -> "KVBatch":
        n = data.num_rows
        seq = np.arange(start_seq, start_seq + n, dtype=np.int64)
        if kinds is None:
            kinds = np.full(n, int(RowKind.INSERT), dtype=np.uint8)
        return KVBatch(data, seq, kinds)

    _KEY_FIELD_ID_OFFSET = 1_000_000_000  # keeps _KEY_ ids disjoint from value ids

    def to_disk_batch(self, key_names: "Sequence[str] | None" = None) -> ColumnBatch:
        """Attach system columns for the on-disk layout. With key_names,
        the trimmed primary key is ALSO duplicated as _KEY_<name> columns at
        the front — the reference KeyValue.schema() layout
        (KeyValue.java:115-120). Key field ids are offset so they never
        collide with the value fields' ids (the reference offsets by the max
        key id for the same reason, KeyValue.createKeyValueFields)."""
        value_schema = self.data.schema
        cols = {}
        fields = []
        if key_names:
            for name in key_names:
                f = value_schema.field(name)
                fields.append(DataField(self._KEY_FIELD_ID_OFFSET + f.id, f"_KEY_{name}", f.type))
                cols[f"_KEY_{name}"] = self.data.column(name)
        disk_schema = kv_disk_schema(value_schema)
        fields.extend(disk_schema.fields)
        cols[SEQUENCE_FIELD_NAME] = Column(self.seq)
        cols[VALUE_KIND_FIELD_NAME] = Column(self.kind.astype(np.int8))
        cols.update(self.data.columns)
        schema = RowType(tuple(fields)) if key_names else disk_schema
        return ColumnBatch(schema, cols)

    @staticmethod
    def from_disk_batch(batch: ColumnBatch, value_schema: RowType) -> "KVBatch":
        seq = batch.column(SEQUENCE_FIELD_NAME).values.astype(np.int64, copy=False)
        kind = batch.column(VALUE_KIND_FIELD_NAME).values.astype(np.uint8)
        data = ColumnBatch(value_schema, {f.name: batch.column(f.name) for f in value_schema.fields})
        return KVBatch(data, seq, kind)

    def drop_deletes(self) -> "KVBatch":
        """Batch reads strip -D/-U rows after merging (reference
        DropDeleteReader.java)."""
        keep = ~np.isin(self.kind, (int(RowKind.DELETE), int(RowKind.UPDATE_BEFORE)))
        return self.filter(keep) if not keep.all() else self
