"""Index manifest IO: the single place that knows the on-disk format.

Parity: /root/reference/paimon-core/.../manifest/IndexManifestFile.java —
the index manifest lists hash-index and deletion-vector index files per
(partition, bucket); the snapshot points at one index manifest.
"""

from __future__ import annotations

from ..fs import FileIO
from ..utils import dumps, loads, new_file_name
from .deletionvectors import IndexFileEntry

__all__ = ["read_index_manifest", "write_index_manifest"]


def read_index_manifest(file_io: FileIO, table_path: str, name: str) -> list[IndexFileEntry]:
    data = file_io.read_bytes(f"{table_path}/manifest/{name}")
    return [IndexFileEntry.from_dict(loads(line)) for line in data.decode().splitlines() if line]


def write_index_manifest(file_io: FileIO, table_path: str, entries: list[IndexFileEntry]) -> str:
    name = new_file_name("index-manifest")
    payload = "\n".join(dumps(e.to_dict()) for e in entries).encode()
    file_io.write_bytes(f"{table_path}/manifest/{name}", payload)
    return name
