"""Snapshots: the versioned root of the table, plus retention/expiry.

Parity: /root/reference/paimon-core/.../Snapshot.java:68 (JSON fields :75-183),
utils/SnapshotManager.java:55 (listing, LATEST/EARLIEST hints),
ExpireSnapshotsImpl (snapshot GC that deletes no-longer-referenced data files).
A snapshot file is immutable JSON written with the atomic-rename CAS; the
LATEST hint is an optimization only — listing is the source of truth.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Iterator

from ..fs import FileIO
from ..utils import dumps, loads, now_millis

__all__ = ["CommitKind", "Snapshot", "SnapshotManager"]


class CommitKind(str, enum.Enum):
    APPEND = "APPEND"
    COMPACT = "COMPACT"
    OVERWRITE = "OVERWRITE"
    ANALYZE = "ANALYZE"


@dataclass
class Snapshot:
    id: int
    schema_id: int
    base_manifest_list: str
    delta_manifest_list: str
    changelog_manifest_list: str | None
    commit_user: str
    commit_identifier: int
    commit_kind: CommitKind
    time_millis: int
    index_manifest: str | None = None
    log_offsets: dict = field(default_factory=dict)
    total_record_count: int | None = None
    delta_record_count: int | None = None
    changelog_record_count: int | None = None
    watermark: int | None = None
    statistics: str | None = None

    def to_json(self) -> str:
        return dumps(
            {
                "version": 3,
                "id": self.id,
                "schemaId": self.schema_id,
                "baseManifestList": self.base_manifest_list,
                "deltaManifestList": self.delta_manifest_list,
                "changelogManifestList": self.changelog_manifest_list,
                "indexManifest": self.index_manifest,
                "commitUser": self.commit_user,
                "commitIdentifier": self.commit_identifier,
                "commitKind": self.commit_kind.value,
                "timeMillis": self.time_millis,
                "logOffsets": self.log_offsets,
                "totalRecordCount": self.total_record_count,
                "deltaRecordCount": self.delta_record_count,
                "changelogRecordCount": self.changelog_record_count,
                "watermark": self.watermark,
                "statistics": self.statistics,
            }
        )

    @staticmethod
    def from_json(s: str | bytes) -> "Snapshot":
        d = loads(s)
        return Snapshot(
            id=d["id"],
            schema_id=d["schemaId"],
            base_manifest_list=d["baseManifestList"],
            delta_manifest_list=d["deltaManifestList"],
            changelog_manifest_list=d.get("changelogManifestList"),
            commit_user=d["commitUser"],
            commit_identifier=d["commitIdentifier"],
            commit_kind=CommitKind(d["commitKind"]),
            time_millis=d["timeMillis"],
            index_manifest=d.get("indexManifest"),
            log_offsets={int(k): v for k, v in (d.get("logOffsets") or {}).items()},
            total_record_count=d.get("totalRecordCount"),
            delta_record_count=d.get("deltaRecordCount"),
            changelog_record_count=d.get("changelogRecordCount"),
            watermark=d.get("watermark"),
            statistics=d.get("statistics"),
        )


class SnapshotManager:
    LATEST = "LATEST"
    EARLIEST = "EARLIEST"

    def __init__(self, file_io: FileIO, table_path: str, cache=None):
        self.file_io = file_io
        self.table_path = table_path
        self.snapshot_dir = f"{table_path}/snapshot"
        # utils.cache manifest cache: snapshot files are immutable per id
        # until deleted (expire invalidates; rollback invalidates before the
        # id can be re-minted with different content)
        self.cache = cache

    def snapshot_path(self, snapshot_id: int) -> str:
        return f"{self.snapshot_dir}/snapshot-{snapshot_id}"

    def snapshot(self, snapshot_id: int) -> Snapshot:
        """The snapshot — falling back to its decoupled changelog copy when
        the snapshot itself already expired (reference
        SnapshotManager.tryGetChangelog): streaming consumers resuming from
        an old position keep reading changelog history."""
        if self.cache is not None and self.cache.enabled:
            key = ("snapshot", self.table_path, snapshot_id)
            cached = self.cache.get(key)
            if cached is not None:
                return cached
        try:
            raw = self.file_io.read_bytes(self.snapshot_path(snapshot_id))
        except FileNotFoundError:
            if self.changelog_exists(snapshot_id):
                return self.changelog(snapshot_id)
            raise
        snap = Snapshot.from_json(raw)
        if self.cache is not None and self.cache.enabled:
            self.cache.put(
                ("snapshot", self.table_path, snapshot_id),
                snap,
                weight=len(raw) * 2,
                file_id=self.snapshot_path(snapshot_id),
            )
        return snap

    def snapshot_exists(self, snapshot_id: int) -> bool:
        return self.file_io.exists(self.snapshot_path(snapshot_id))

    # ---- decoupled changelogs (reference Changelog.java) ----------------
    @property
    def changelog_dir(self) -> str:
        return f"{self.table_path}/changelog"

    def changelog_path(self, snapshot_id: int) -> str:
        return f"{self.changelog_dir}/changelog-{snapshot_id}"

    def changelog(self, snapshot_id: int) -> Snapshot:
        return Snapshot.from_json(self.file_io.read_bytes(self.changelog_path(snapshot_id)))

    def changelog_exists(self, snapshot_id: int) -> bool:
        return self.file_io.exists(self.changelog_path(snapshot_id))

    def changelog_ids(self) -> list[int]:
        out = []
        for st in self.file_io.list_files(self.changelog_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("changelog-"):
                try:
                    out.append(int(base[len("changelog-") :]))
                except ValueError:
                    continue
        return sorted(out)

    # ---- discovery -----------------------------------------------------
    def _hint(self, name: str) -> int | None:
        try:
            return int(self.file_io.read_text(f"{self.snapshot_dir}/{name}"))
        except Exception:
            return None

    def _listed_ids(self) -> list[int]:
        out = []
        for st in self.file_io.list_files(self.snapshot_dir):
            base = st.path.rsplit("/", 1)[-1]
            if base.startswith("snapshot-"):
                try:
                    out.append(int(base[len("snapshot-") :]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_snapshot_id(self) -> int | None:
        # latest-pointer cache: a cached id L is still the latest iff
        # snapshot-L exists and snapshot-(L+1) does not (ids are contiguous
        # and monotonic), so validation is two stat calls instead of
        # hint-read + forward walk + listing fallback. Self-correcting under
        # concurrent commits (L+1 appears -> probe fails -> full resolve)
        # and rollback (L vanishes -> probe fails).
        cache_key = ("latest", self.table_path)
        if self.cache is not None and self.cache.enabled:
            cached = self.cache.get(cache_key)
            if cached is not None and self.snapshot_exists(cached) and not self.snapshot_exists(cached + 1):
                return cached
        latest = self._resolve_latest_id()
        if latest is not None and self.cache is not None and self.cache.enabled:
            self.cache.put(cache_key, latest, weight=64)
        return latest

    def _resolve_latest_id(self) -> int | None:
        hint = self._hint(self.LATEST)
        if hint is not None:
            # the hint may lag; walk forward (reference SnapshotManager)
            nxt = hint + 1
            while self.snapshot_exists(nxt):
                hint, nxt = nxt, nxt + 1
            if self.snapshot_exists(hint):
                return hint
        ids = self._listed_ids()
        return ids[-1] if ids else None

    def earliest_snapshot_id(self) -> int | None:
        hint = self._hint(self.EARLIEST)
        if hint is not None and self.snapshot_exists(hint):
            return hint
        ids = self._listed_ids()
        return ids[0] if ids else None

    def latest_snapshot(self) -> Snapshot | None:
        sid = self.latest_snapshot_id()
        return self.snapshot(sid) if sid is not None else None

    def snapshots(self) -> Iterator[Snapshot]:
        for sid in self._listed_ids():
            yield self.snapshot(sid)

    def snapshot_count(self) -> int:
        return len(self._listed_ids())

    # ---- hints ---------------------------------------------------------
    def commit_latest_hint(self, snapshot_id: int) -> None:
        self.file_io.try_overwrite(f"{self.snapshot_dir}/{self.LATEST}", str(snapshot_id).encode())
        if self.cache is not None and self.cache.enabled:
            # seed the latest-pointer cache; a stale seed (concurrent commit
            # raced ahead) fails the exists(L+1) validation and re-resolves
            self.cache.put(("latest", self.table_path), snapshot_id, weight=64)

    def commit_earliest_hint(self, snapshot_id: int) -> None:
        self.file_io.try_overwrite(f"{self.snapshot_dir}/{self.EARLIEST}", str(snapshot_id).encode())

    # ---- time travel ---------------------------------------------------
    def earlier_or_equal_time_millis(self, millis: int) -> Snapshot | None:
        best = None
        for snap in self.snapshots():
            if snap.time_millis <= millis:
                best = snap
            else:
                break
        return best

    def latest_snapshot_of_user(self, user: str) -> Snapshot | None:
        """Walk backward from latest, stop at the first match — O(gap), not
        O(history) (reference SnapshotManager does the same backward walk)."""
        for snap in self.snapshots_of_user(user):
            return snap
        return None

    def snapshots_of_user(self, user: str):
        """Yield this user's snapshots newest-first (lazy backward walk, so
        callers that stop at the first acceptable one stay O(gap))."""
        latest = self.latest_snapshot_id()
        earliest = self.earliest_snapshot_id()
        if latest is None or earliest is None:
            return
        for sid in range(latest, earliest - 1, -1):
            if not self.snapshot_exists(sid):
                continue
            snap = self.snapshot(sid)
            if snap.commit_user == user:
                yield snap

    def snapshots_of_user_with_identifier(self, user: str, identifier: int) -> list[Snapshot]:
        """All of this user's snapshots carrying `identifier`, walking
        backward and stopping once the user's identifiers drop below it
        (identifiers are monotonic per user)."""
        latest = self.latest_snapshot_id()
        earliest = self.earliest_snapshot_id()
        out: list[Snapshot] = []
        if latest is None or earliest is None:
            return out
        for sid in range(latest, earliest - 1, -1):
            if not self.snapshot_exists(sid):
                continue
            snap = self.snapshot(sid)
            if snap.commit_user != user:
                continue
            if snap.commit_identifier == identifier:
                out.append(snap)
            elif snap.commit_identifier < identifier:
                break
        return out
