"""Dynamic bucket mode: durable key-hash -> bucket assignment.

Parity: /root/reference/paimon-core/.../index/ — HashBucketAssigner.java:37 /
SimpleHashBucketAssigner (single-writer), PartitionIndex (key-hash set per
bucket persisted as hash index files in the index manifest). A PK table with
bucket = -1 assigns each new key to a non-full bucket and pins it there
forever; the per-bucket hash sets are the durable record.

Vectorized: assignment of a batch is one membership probe (np.isin against
each bucket's sorted hash array) + one allocation pass for the misses.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from ..utils.compression import zstd_compress, zstd_decompress

from ..fs import FileIO
from ..utils import new_file_name
from .deletionvectors import IndexFileEntry

__all__ = ["HashIndexFile", "SimpleHashBucketAssigner"]


class HashIndexFile:
    """One file per (partition, bucket): the sorted uint64 key hashes living
    in that bucket (reference index/HashIndexFile — int hashes in sequence)."""

    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.index_dir = f"{table_path}/index"

    def write(self, hashes: np.ndarray) -> str:
        name = new_file_name("index-hash")
        payload = zstd_compress(np.sort(hashes.astype(np.uint64)).tobytes())
        self.file_io.write_bytes(f"{self.index_dir}/{name}", payload)
        return name

    def read(self, name: str) -> np.ndarray:
        raw = zstd_decompress(self.file_io.read_bytes(f"{self.index_dir}/{name}"))
        return np.frombuffer(raw, dtype=np.uint64).copy()


@dataclass
class _PartitionIndex:
    buckets: dict[int, np.ndarray]  # bucket -> sorted uint64 hashes
    dirty: set


class SimpleHashBucketAssigner:
    """Single-writer assigner (reference SimpleHashBucketAssigner): suitable
    whenever one process owns all buckets of the partitions it writes."""

    def __init__(
        self,
        index_file: HashIndexFile,
        target_bucket_rows: int,
        initial_buckets: int | None = None,
        assign_id: int = 0,
        num_assigners: int = 1,
    ):
        self.index_file = index_file
        self.target = target_bucket_rows
        # dynamic-bucket.initial-buckets: new keys round-robin across this
        # many buckets from the start (write parallelism before any bucket
        # fills); dynamic-bucket.assigner-parallelism: this assigner only
        # creates buckets striped bucket % num_assigners == assign_id
        # (reference HashBucketAssigner.assignBucket)
        self.initial_buckets = initial_buckets
        self.assign_id = assign_id
        self.num_assigners = max(1, num_assigners)
        self._partitions: dict[tuple, _PartitionIndex] = {}
        self._rr: dict[tuple, int] = {}  # per-partition round-robin cursor

    def _allocate_new(self, partition: tuple, counts: dict[int, int]) -> int:
        """Bucket for a brand-new key: striped to this assigner, round-robin
        over the initial window while any of it has room, then growing."""
        p = self.num_assigners
        width = max(1, ((self.initial_buckets or 1) + p - 1) // p)
        rr = self._rr.get(partition, 0)
        base = 0
        while True:
            window = [self.assign_id + (base + j) * p for j in range(width)]
            open_ = [b for b in window if counts.get(b, 0) < self.target]
            if open_:
                b = open_[rr % len(open_)]
                self._rr[partition] = rr + 1
                return b
            base += width

    def bootstrap(self, partition: tuple, bucket_indexes: dict[int, np.ndarray]) -> None:
        self._partitions[partition] = _PartitionIndex(
            {b: np.sort(h.astype(np.uint64)) for b, h in bucket_indexes.items()}, set()
        )

    def assign(self, partition: tuple, hashes: np.ndarray) -> np.ndarray:
        """(n,) uint64 key hashes -> (n,) int32 buckets."""
        pi = self._partitions.setdefault(partition, _PartitionIndex({}, set()))
        n = len(hashes)
        out = np.full(n, -1, dtype=np.int32)
        # existing membership
        for b, hs in pi.buckets.items():
            if len(hs) == 0:
                continue
            unassigned = out == -1
            if not unassigned.any():
                break
            idx = np.searchsorted(hs, hashes)
            hit = (idx < len(hs)) & (hs[np.minimum(idx, len(hs) - 1)] == hashes)
            out = np.where(unassigned & hit, b, out)
        # allocate the rest (duplicates within the batch share one slot)
        missing = np.flatnonzero(out == -1)
        if len(missing):
            uniq, inv = np.unique(hashes[missing], return_inverse=True)
            alloc = np.empty(len(uniq), dtype=np.int32)
            counts = {b: len(hs) for b, hs in pi.buckets.items()}
            for i in range(len(uniq)):
                b = self._allocate_new(partition, counts)
                alloc[i] = b
                counts[b] = counts.get(b, 0) + 1
            out[missing] = alloc[inv]
            for b in np.unique(alloc):
                new_hashes = uniq[alloc == b]
                old = pi.buckets.get(b, np.empty(0, np.uint64))
                pi.buckets[b] = np.unique(np.concatenate([old, new_hashes]))
                pi.dirty.add(int(b))
        return out

    def prepare_commit(self, total_buckets_hint: int = -1) -> dict[tuple, list[IndexFileEntry]]:
        """Write updated hash index files for dirty buckets."""
        out: dict[tuple, list[IndexFileEntry]] = {}
        for partition, pi in self._partitions.items():
            entries = []
            for b in sorted(pi.dirty):
                name = self.index_file.write(pi.buckets[b])
                entries.append(IndexFileEntry("HASH_INDEX", partition, b, name, len(pi.buckets[b])))
            if entries:
                out[partition] = entries
            pi.dirty.clear()
        return out
