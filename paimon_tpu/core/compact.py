"""LSM compaction: universal strategy, upgrade-vs-rewrite tasks, rewriter.

Parity: /root/reference/paimon-core/.../mergetree/compact/ —
  UniversalCompaction.java:42 (RocksDB-style: size-amplification trigger
  pickForSizeAmp:114, size-ratio pickForSizeRatio:150, run-count trigger
  pick:100-108, optional full-compact interval :73-80),
  MergeTreeCompactManager.java:67 (triggerCompaction:115-176, dropDelete rule
  :148-158), MergeTreeCompactTask.java:40 (doCompact:77-105 partitions the
  unit into sections, *upgrades* large non-overlapping files vs *rewrites*
  overlapping/small ones), MergeTreeCompactRewriter.java:76-84 (rewrite =
  the same merge kernel as the read path + rolling writer at outputLevel).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ..options import CoreOptions
from ..utils import now_millis
from .datafile import DataFileMeta, KeyValueFileReaderFactory, KeyValueFileWriterFactory
from .kv import KVBatch
from .levels import IntervalPartition, Levels, SortedRun
from .mergefn import MergeExecutor

__all__ = ["CompactUnit", "CompactResult", "UniversalCompaction", "MergeTreeCompactRewriter", "MergeTreeCompactManager"]


@dataclass
class CompactUnit:
    output_level: int
    files: list[DataFileMeta]
    file_num_based: bool = False


@dataclass
class CompactResult:
    before: list[DataFileMeta] = field(default_factory=list)
    after: list[DataFileMeta] = field(default_factory=list)
    changelog: list[DataFileMeta] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.before and not self.after


class UniversalCompaction:
    """Pick which sorted runs to compact (reference UniversalCompaction)."""

    def __init__(
        self,
        max_size_amp_percent: int = 200,
        size_ratio_percent: int = 1,
        num_run_compaction_trigger: int = 5,
        optimization_interval_millis: int | None = None,
        max_file_num: int = 50,
    ):
        self.max_size_amp = max_size_amp_percent
        self.size_ratio = size_ratio_percent
        self.num_run_trigger = num_run_compaction_trigger
        self.opt_interval = optimization_interval_millis
        # bounds ONE size-ratio pick's input file count so a single
        # compaction cannot balloon (reference compaction.max.file-num)
        self.max_file_num = max_file_num
        self._last_opt_millis = now_millis()

    def pick(self, num_levels: int, runs: list[tuple[int, SortedRun]]) -> CompactUnit | None:
        max_level = num_levels - 1
        if self.opt_interval is not None and now_millis() - self._last_opt_millis >= self.opt_interval:
            self._last_opt_millis = now_millis()
            return self._unit(runs, max_level, len(runs))
        # 1. size amplification
        unit = self._pick_size_amp(max_level, runs)
        if unit is not None:
            return unit
        # 2. size ratio
        unit = self._pick_size_ratio(max_level, runs)
        if unit is not None:
            return unit
        # 3. run count
        if len(runs) > self.num_run_trigger:
            candidate = len(runs) - self.num_run_trigger + 1
            return self._unit(runs, max_level, candidate, file_num_based=True)
        return None

    def _pick_size_amp(self, max_level: int, runs) -> CompactUnit | None:
        if len(runs) <= self.num_run_trigger:
            return None
        candidate = sum(r.total_size() for _, r in runs[:-1])
        earliest = runs[-1][1].total_size()
        if earliest and candidate * 100 / earliest >= self.max_size_amp:
            return self._unit(runs, max_level, len(runs))
        return None

    def _pick_size_ratio(self, max_level: int, runs) -> CompactUnit | None:
        if len(runs) <= self.num_run_trigger:
            return None
        candidate_size = runs[0][1].total_size()
        count = 1
        files = len(runs[0][1].files)
        for lv, run in runs[1:]:
            if candidate_size * (100.0 + self.size_ratio) / 100.0 < run.total_size():
                break
            if files + len(run.files) > self.max_file_num:
                break
            candidate_size += run.total_size()
            files += len(run.files)
            count += 1
        if count > 1:
            return self._unit(runs, max_level, count)
        return None

    @staticmethod
    def _unit(runs, max_level: int, count: int, file_num_based: bool = False) -> CompactUnit:
        """Choose the output level for the first `count` runs (reference
        UniversalCompaction.createUnit:179-205). The tentative output is one
        level below the first excluded run; when that floor is level 0 the
        unit is extended through the remaining level-0 runs AND the first
        non-zero-level run (else its level would end up holding two runs,
        breaking the one-run-per-level invariant), outputting at that run's
        level — or max_level when everything got absorbed."""
        if count < len(runs):
            output = runs[count][0] - 1
            if output <= 0:
                while count < len(runs):
                    level = runs[count][0]
                    count += 1
                    if level != 0:
                        output = level
                        break
        if count == len(runs):
            output = max_level
        files = [f for _, r in runs[:count] for f in r.files]
        return CompactUnit(output, files, file_num_based)

    def force_full(self, num_levels: int, runs) -> CompactUnit | None:
        return self._unit(runs, num_levels - 1, len(runs)) if runs else None


class MergeTreeCompactRewriter:
    """Merge-read the unit's sections and rewrite at the output level —
    the same kernel as the read path."""

    def __init__(
        self,
        reader_factory: KeyValueFileReaderFactory,
        writer_factory: KeyValueFileWriterFactory,
        merge_executor: MergeExecutor,
        deletion_vectors: dict | None = None,
        emit_full_changelog: bool = False,
        row_deduplicate: bool = True,
        expire_predicate=None,
    ):
        self.reader_factory = reader_factory
        self.writer_factory = writer_factory
        self.merge = merge_executor
        # record-level TTL: expired rows are physically dropped on rewrite
        self.expire_predicate = expire_predicate
        # DV'd rows must be dropped during the rewrite (the commit purges the
        # dead files' DVs afterwards) — else compaction resurrects them
        self.deletion_vectors = deletion_vectors or {}
        # full-compaction changelog producer (reference
        # FullChangelogMergeTreeCompactRewriter:43)
        self.emit_full_changelog = emit_full_changelog
        self.row_deduplicate = row_deduplicate

    def _read(self, f: DataFileMeta) -> KVBatch:
        kv = self.reader_factory.read(f)
        dv = self.deletion_vectors.get(f.file_name)
        if dv is not None:
            mask = ~dv.deleted_mask(kv.num_rows)
            if not mask.all():
                kv = kv.filter(mask)
        if self.expire_predicate is not None and kv.num_rows:
            keep = self.expire_predicate.eval(kv.data)
            if not keep.all():
                kv = kv.filter(keep)
        return kv

    def rewrite(
        self, sections: list[list[SortedRun]], output_level: int, drop_delete: bool
    ) -> tuple[list[DataFileMeta], list[DataFileMeta]]:
        """Returns (new files, changelog files)."""
        return self.rewrite_complete(self.rewrite_dispatch(sections, output_level), output_level, drop_delete)

    def rewrite_pipelined(
        self,
        sections: list[list[SortedRun]],
        output_level: int,
        drop_delete: bool,
        depth: int,
        parallelism: int | None = None,
    ) -> tuple[list[DataFileMeta], list[DataFileMeta]]:
        """Pipelined rewrite: section i+1's file reads run on pipeline
        workers while section i's merge executes on device, and section i's
        output encode overlaps the dispatch of section i+1's merge (the
        resolve-previous-after-dispatch-next stagger below). Output lists are
        in section order — identical to rewrite() (the sequential path reads
        EVERY section before the first merge; this one keeps at most depth+1
        sections' inputs alive)."""
        from ..parallel.pipeline import SplitPipeline

        out: list[DataFileMeta] = []
        changelog: list[DataFileMeta] = []
        pipe = SplitPipeline(parallelism, depth, stage="compact")
        read_section = lambda section: self._read_section(section, output_level)
        pending = None  # previous section's (merge handle, old_top)
        for kv, old_top, seq_ascending in pipe.map_ordered(sections, read_section):
            handle = self.merge.merge_async(kv, seq_ascending=seq_ascending)
            if pending is not None:
                self._write_section(pending, output_level, drop_delete, out, changelog)
            pending = (handle, old_top)
        if pending is not None:
            self._write_section(pending, output_level, drop_delete, out, changelog)
        return out, changelog

    def _write_section(self, job, output_level: int, drop_delete: bool, out, changelog) -> None:
        """Resolve one section's merge and encode its output (the shared tail
        of rewrite_complete and rewrite_pipelined)."""
        handle, old_top = job
        merged = self.merge.merge_resolve(handle)
        if drop_delete:
            merged = merged.drop_deletes()
        if self.emit_full_changelog and drop_delete:
            cl = self._section_changelog(old_top, merged)
            if cl.num_rows:
                changelog.extend(
                    self.writer_factory.write(cl, level=0, file_source="compact", prefix="changelog")
                )
        out.extend(self.writer_factory.write(merged, output_level, file_source="compact"))

    def _read_section(self, section: list[SortedRun], output_level: int):
        """Read one section's runs in merge order: (concatenated KVBatch,
        old top-level batches for the changelog diff, seq_ascending) — the
        shared read head of every rewrite mode."""
        from ..parallel.pipeline import bounded_map
        from .read import order_runs_for_merge

        runs, seq_ascending = order_runs_for_merge(section)
        files = [f for run in runs for f in run.files]
        # per-file reads fan out over the shared pool (order preserved, so
        # the concatenated runs — and the merge — are bit-identical to the
        # old serial loop); this is leaf work per the pool contract
        batches = bounded_map(self._read, files)
        old_top = [b for f, b in zip(files, batches) if f.level == output_level]
        return KVBatch.concat(batches), old_top, seq_ascending

    def rewrite_dispatch(self, sections: list[list[SortedRun]], output_level: int):
        """Phase 1: read every section's runs and dispatch their merges.
        Under a mesh context the merges of ALL sections (and all buckets
        whose compactions dispatched in the same batch window) execute in
        batched shard_map calls over the mesh; with the MeshExecutor active
        the section reads additionally stream through the SplitPipeline
        feeder (one prefetch lane per device) instead of running serially."""
        import threading

        from ..parallel.executor import current_mesh_context
        from ..parallel.pipeline import PIPELINE_THREAD_PREFIX

        ctx = current_mesh_context()
        # no feeder-in-feeder: when this dispatch already runs on a pipeline
        # worker (table/write.compact fans buckets out), the serial loop below
        # still fans its file reads over the shared pool
        in_worker = threading.current_thread().name.startswith(PIPELINE_THREAD_PREFIX)
        if (
            ctx is not None
            and getattr(ctx, "plans_globally", False)
            and len(sections) > 1
            and not in_worker
        ):
            from ..parallel.pipeline import SplitPipeline

            lanes = ctx.feeder_lanes
            pipe = SplitPipeline(parallelism=lanes, depth=lanes, stage="compact")
            return [
                (self.merge.merge_async(kv, seq_ascending=sa), old_top)
                for kv, old_top, sa in pipe.map_ordered(
                    sections, lambda s: self._read_section(s, output_level)
                )
            ]
        jobs = []
        for section in sections:
            kv, old_top, seq_ascending = self._read_section(section, output_level)
            jobs.append((self.merge.merge_async(kv, seq_ascending=seq_ascending), old_top))
        return jobs

    def rewrite_complete(
        self, jobs, output_level: int, drop_delete: bool
    ) -> tuple[list[DataFileMeta], list[DataFileMeta]]:
        """Phase 2: resolve merges, emit changelog, write output files."""
        out: list[DataFileMeta] = []
        changelog: list[DataFileMeta] = []
        for job in jobs:
            self._write_section(job, output_level, drop_delete, out, changelog)
        return out, changelog

    def _section_changelog(self, old_top: list[KVBatch], merged: KVBatch) -> KVBatch:
        from ..data.keys import encode_key_lanes, exact_string_pool
        from ..types import TypeRoot
        from .changelog import full_compaction_changelog

        before = KVBatch.concat(old_top) if old_top else merged.slice(0, 0)
        key_names = self.merge.key_names
        pools = {}
        for k in key_names:
            root = merged.data.schema.field(k).type.root
            if root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
                pools[k] = exact_string_pool([before.data.column(k), merged.data.column(k)])
        lanes_before = encode_key_lanes(before.data, key_names, pools)
        lanes_after = encode_key_lanes(merged.data, key_names, pools)
        return full_compaction_changelog(
            before, merged, lanes_before, lanes_after, row_deduplicate=self.row_deduplicate
        )

    def upgrade(self, file: DataFileMeta, output_level: int) -> DataFileMeta:
        return file.upgrade(output_level)


class MergeTreeCompactManager:
    """Decides when and what to compact for one bucket's Levels. Execution is
    synchronous-on-demand here (deterministic); the async thread-pool offload
    of the reference maps to the parallel runtime's bucket sharding instead."""

    def __init__(
        self,
        levels: Levels,
        strategy: UniversalCompaction,
        rewriter: MergeTreeCompactRewriter,
        options: CoreOptions,
    ):
        self.levels = levels
        self.strategy = strategy
        self.rewriter = rewriter
        self.options = options

    def should_wait_for_compaction(self) -> bool:
        return self.levels.number_of_sorted_runs() > self.options.num_sorted_runs_stop_trigger

    def trigger_compaction(self, full: bool = False) -> CompactResult | None:
        from ..metrics import registry, timed
        from ..parallel.executor import current_mesh_context
        from ..parallel.mesh_exec import maybe_mesh_exec
        from ..parallel.pipeline import pipeline_config

        depth, parallelism = pipeline_config(self.options)
        g = registry.group("compaction")
        with timed(g.histogram("duration_ms")):
            # merge.engine = mesh and no context installed yet (standalone
            # compaction, not under a table-write batch window): install the
            # MeshExecutor so this bucket's section merges run as batched
            # shard_maps; no-op (yields None) on 1 device — cpu fallback
            with maybe_mesh_exec(self.options) as mex:
                if mex is None and depth > 0 and current_mesh_context() is None:
                    # pipelined route: section reads / device merges / output
                    # encodes overlap (rewrite_pipelined) instead of reading
                    # every input before the first merge. Mesh execution keeps
                    # the dispatch/complete split (all merges in shard_maps).
                    plan = self._plan_unit(full)
                    result = self._complete_pipelined(plan, depth, parallelism)
                else:
                    state = self.compact_dispatch(full)
                    result = self.compact_complete(state)
        if result is not None and not result.is_empty():
            g.counter("compactions").inc()
            g.counter("files_rewritten").inc(len(result.before))
        return result

    def _plan_unit(self, full: bool = False):
        """Pick the unit and classify upgrade-vs-rewrite (reference
        MergeTreeCompactTask.doCompact) WITHOUT reading any input. Returns
        (unit, drop_delete, result, rewrite_sections) or None."""
        runs = self.levels.level_sorted_runs()
        if full:
            unit = self.strategy.force_full(self.levels.num_levels, runs)
        else:
            unit = self.strategy.pick(self.levels.num_levels, runs)
        if unit is None or not unit.files:
            return None
        # drop deletes iff the output is the highest non-empty level's floor
        # (reference MergeTreeCompactManager.triggerCompaction :148-158)
        drop_delete = unit.output_level != 0 and unit.output_level >= self.levels.non_empty_highest_level()
        result = CompactResult()
        sections = IntervalPartition(unit.files).partition()
        rewrite_sections: list[list[SortedRun]] = []
        min_rewrite_size = self.options.target_file_size  # files below target get merged together
        dv_files = set(self.rewriter.deletion_vectors)
        # full-compaction changelog must SEE every row reaching the top level:
        # upgrades bypass rewrite() and would emit nothing (reference forces
        # rewrite when upgrading to maxLevel under the full changelog producer)
        force_rewrite = self.rewriter.emit_full_changelog and drop_delete
        for section in sections:
            if len(section) == 1:
                for f in section[0].files:
                    if f.file_name in dv_files or (force_rewrite and f.level != unit.output_level):
                        # physically drop DV'd rows (the commit purges the DV)
                        rewrite_sections.append([SortedRun([f])])
                    elif self._can_upgrade(f, unit.output_level, drop_delete, min_rewrite_size):
                        if f.level != unit.output_level:
                            up = self.rewriter.upgrade(f, unit.output_level)
                            result.before.append(f)
                            result.after.append(up)
                        # same level: untouched
                    else:
                        rewrite_sections.append([SortedRun([f])])
            else:
                rewrite_sections.append(section)
        return (unit, drop_delete, result, rewrite_sections)

    def compact_dispatch(self, full: bool = False):
        """Phase 1: plan the unit, then read inputs and dispatch the section
        merges (under a MeshBatchContext every bucket's merges batch into one
        shard_map). Returns opaque state for compact_complete, or None when
        nothing to compact."""
        plan = self._plan_unit(full)
        if plan is None:
            return None
        unit, drop_delete, result, rewrite_sections = plan
        jobs = self.rewriter.rewrite_dispatch(rewrite_sections, unit.output_level) if rewrite_sections else []
        return (unit, drop_delete, result, rewrite_sections, jobs)

    def compact_complete(self, state) -> CompactResult | None:
        """Phase 2: resolve section merges, write outputs, update Levels."""
        if state is None:
            return None
        unit, drop_delete, result, rewrite_sections, jobs = state
        after, changelog = (
            self.rewriter.rewrite_complete(jobs, unit.output_level, drop_delete)
            if rewrite_sections
            else ([], [])
        )
        return self._finish(unit, drop_delete, result, rewrite_sections, after, changelog)

    def _complete_pipelined(self, plan, depth: int, parallelism: int | None) -> CompactResult | None:
        """Pipelined phase 2: sections stream through read -> merge -> encode
        with bounded readahead (rewrite_pipelined) — same outputs, same
        order, without materializing every section's input first."""
        if plan is None:
            return None
        unit, drop_delete, result, rewrite_sections = plan
        after, changelog = (
            self.rewriter.rewrite_pipelined(
                rewrite_sections, unit.output_level, drop_delete, depth, parallelism
            )
            if rewrite_sections
            else ([], [])
        )
        return self._finish(unit, drop_delete, result, rewrite_sections, after, changelog)

    def _finish(
        self, unit, drop_delete, result: CompactResult, rewrite_sections, after, changelog
    ) -> CompactResult:
        """Shared bookkeeping tail: fold rewrite outputs into the result,
        invalidate dead cache entries, update Levels."""
        if rewrite_sections:
            flat_before = [f for sec in rewrite_sections for r in sec for f in r.files]
            result.before.extend(flat_before)
            result.after.extend(after)
            result.changelog.extend(changelog)
            # rewritten inputs left the live LSM view: drop their decoded
            # batches so the byte budget tracks the hot working set (upgraded
            # files in result.before keep the same physical file — NOT
            # invalidated; a time-travel read of a rewritten file re-decodes)
            from ..utils.cache import invalidate_data_file

            for f in flat_before:
                invalidate_data_file(f.file_name)
        if not result.is_empty():
            self.levels.update(result.before, result.after)
        return result

    @staticmethod
    def _can_upgrade(f: DataFileMeta, output_level: int, drop_delete: bool, min_size: int) -> bool:
        if f.level == 0 and f.file_size < min_size:
            return False  # merge small level-0 files together
        if drop_delete and f.delete_row_count > 0:
            return False  # must rewrite to physically drop deletes at top level
        return True
