"""Deletion vectors: per-file bitmaps of deleted row positions.

Parity: /root/reference/paimon-core/.../deletionvectors/ —
DeletionVector.java:39 / BitmapDeletionVector (RoaringBitmap32 of positions),
DeletionVectorsMaintainer, DeletionVectorsIndexFile (many DVs packed in one
index file, located via the index manifest), ApplyDeletionVectorReader.
Representation here: sorted uint32 position arrays (vectorized membership via
searchsorted; zstd-compressed on disk) — the numpy-native equivalent of a
roaring bitmap at lake-file cardinalities.

Index container ("index-<uuid>"):
  [4B magic "PTDV"][4B header len][JSON header][blobs]
  header = {data_file_name: {"offset": o, "length": l, "cardinality": c}}
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from typing import Iterable, Mapping

import numpy as np
from ..utils.compression import zstd_compress, zstd_decompress

from ..fs import FileIO
from ..utils import new_file_name

__all__ = ["DeletionVector", "DeletionVectorsIndexFile", "IndexFileEntry", "DeletionVectorsMaintainer"]

_MAGIC = b"PTDV"


class DeletionVector:
    """Sorted unique uint32 row positions marked deleted."""

    def __init__(self, positions: np.ndarray | None = None):
        self.positions = (
            np.unique(positions.astype(np.uint32)) if positions is not None and len(positions) else np.empty(0, np.uint32)
        )

    @property
    def cardinality(self) -> int:
        return len(self.positions)

    def is_empty(self) -> bool:
        return len(self.positions) == 0

    def merge(self, other: "DeletionVector") -> "DeletionVector":
        return DeletionVector(np.concatenate([self.positions, other.positions]))

    def is_deleted(self, position: int) -> bool:
        i = np.searchsorted(self.positions, position)
        return bool(i < len(self.positions) and self.positions[i] == position)

    def deleted_mask(self, num_rows: int) -> np.ndarray:
        mask = np.zeros(num_rows, dtype=np.bool_)
        pos = self.positions[self.positions < num_rows]
        mask[pos] = True
        return mask

    def to_bytes(self) -> bytes:
        return zstd_compress(self.positions.tobytes())

    @staticmethod
    def from_bytes(data: bytes) -> "DeletionVector":
        raw = zstd_decompress(data)
        return DeletionVector(np.frombuffer(raw, dtype=np.uint32).copy())


@dataclass(frozen=True)
class IndexFileEntry:
    """One index file registered for a (partition, bucket) (reference
    IndexManifestEntry + IndexFileMeta)."""

    kind: str  # "DELETION_VECTORS" | "HASH_INDEX"
    partition: tuple
    bucket: int
    file_name: str
    row_count: int

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "partition": list(self.partition),
            "bucket": self.bucket,
            "fileName": self.file_name,
            "rowCount": self.row_count,
        }

    @staticmethod
    def from_dict(d: dict) -> "IndexFileEntry":
        return IndexFileEntry(d["kind"], tuple(d["partition"]), d["bucket"], d["fileName"], d["rowCount"])


class DeletionVectorsIndexFile:
    """Reads/writes the packed DV container in the table's index/ dir."""

    def __init__(self, file_io: FileIO, table_path: str, target_size: int = 2 << 20):
        self.file_io = file_io
        self.index_dir = f"{table_path}/index"
        # deletion-vector.index-file.target-size: containers roll at this
        # size into a chain (header carries "__next__"); callers keep the
        # single-name contract, readers follow the chain
        self.target_size = max(1, target_size)

    def write(self, dvs: Mapping[str, DeletionVector]) -> tuple[str, int]:
        items = sorted(dvs.items())
        total = sum(dv.cardinality for _, dv in dvs.items())
        chunks: list[list] = [[]]
        size = 0
        for data_file, dv in items:
            blob = dv.to_bytes()
            if size and size + len(blob) > self.target_size:
                chunks.append([])
                size = 0
            chunks[-1].append((data_file, blob, dv.cardinality))
            size += len(blob)
        next_name: str | None = None
        for chunk in reversed(chunks):  # write tail first to know its name
            header: dict = {}
            blobs: list[bytes] = []
            offset = 0
            for data_file, blob, card in chunk:
                header[data_file] = {"offset": offset, "length": len(blob), "cardinality": card}
                blobs.append(blob)
                offset += len(blob)
            if next_name is not None:
                header["__next__"] = next_name
            hdr = json.dumps(header).encode()
            payload = _MAGIC + struct.pack("<I", len(hdr)) + hdr + b"".join(blobs)
            next_name = new_file_name("index")
            self.file_io.write_bytes(f"{self.index_dir}/{next_name}", payload)
        return next_name, total

    def read_all(self, name: str | None) -> dict[str, DeletionVector]:
        out: dict[str, DeletionVector] = {}
        while name is not None:
            data = self.file_io.read_bytes(f"{self.index_dir}/{name}")
            assert data[:4] == _MAGIC, "bad deletion-vector index magic"
            (hlen,) = struct.unpack("<I", data[4:8])
            header = json.loads(data[8 : 8 + hlen])
            blob = data[8 + hlen :]
            name = header.pop("__next__", None)
            for data_file, meta in header.items():
                out[data_file] = DeletionVector.from_bytes(
                    blob[meta["offset"] : meta["offset"] + meta["length"]]
                )
        return out

    def delete(self, name: str) -> None:
        self.file_io.delete(f"{self.index_dir}/{name}")

    def chain_names(self, name: str) -> list[str]:
        """All container files of a chain starting at `name` (for cleaners
        and cloners, which must treat the chain as one logical file)."""
        out = []
        while name is not None:
            out.append(name)
            try:
                data = self.file_io.read_bytes(f"{self.index_dir}/{name}")
                (hlen,) = struct.unpack("<I", data[4:8])
                name = json.loads(data[8 : 8 + hlen]).get("__next__")
            except (FileNotFoundError, OSError):
                break
        return out


class DeletionVectorsMaintainer:
    """Accumulates per-data-file deletions for one (partition, bucket) and
    emits the replacement index file at commit time."""

    def __init__(self, index_file: DeletionVectorsIndexFile, restored: Mapping[str, DeletionVector] | None = None):
        self.index_file = index_file
        self.dvs: dict[str, DeletionVector] = dict(restored or {})

    def notify_deletion(self, data_file: str, positions: np.ndarray) -> None:
        dv = DeletionVector(positions)
        if data_file in self.dvs:
            dv = self.dvs[data_file].merge(dv)
        self.dvs[data_file] = dv

    def remove_file(self, data_file: str) -> None:
        """Compaction rewrote the file: its DV is obsolete."""
        self.dvs.pop(data_file, None)

    def prepare_commit(self, partition: tuple, bucket: int) -> IndexFileEntry | None:
        live = {f: dv for f, dv in self.dvs.items() if not dv.is_empty()}
        if not live:
            return None
        name, total = self.index_file.write(live)
        return IndexFileEntry("DELETION_VECTORS", partition, bucket, name, total)
