"""The snapshot-CAS commit protocol.

Parity: /root/reference/paimon-core/.../operation/FileStoreCommitImpl.java
(:219 commit, :202-207 filterCommitted via latestSnapshotOfUser, :678 tryCommit
loop, :774 tryCommitOnce, :843-852 manifest merging, :942 atomic snapshot
write, :917 cleanUpTmpManifests) and table/sink/TableCommitImpl.java:183
(filterAndCommit idempotent replay).

One logical commit produces up to two snapshots: APPEND (the writers' new
level-0 files + input changelog) then COMPACT (compaction before/after), same
as the reference — so a crashed commit retried after the APPEND snapshot only
re-applies the missing COMPACT part via commit-identifier filtering.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Callable, Sequence

from ..fs import FileIO
from ..options import CoreOptions
from ..resilience.faults import crash_point
from ..utils import dumps, loads, new_file_name, now_millis
from .manifest import (
    CommitMessage,
    FileKind,
    ManifestCommittable,
    ManifestEntry,
    ManifestFile,
    ManifestFileMeta,
    ManifestList,
    merge_entries,
    merge_entries_keep_deletes,
)
from .snapshot import CommitKind, Snapshot, SnapshotManager

# Batch jobs commit once with this sentinel identifier (reference
# BatchWriteBuilder.COMMIT_IDENTIFIER = Long.MAX_VALUE); it never enters the
# monotonic per-user streaming sequence.
BATCH_COMMIT_IDENTIFIER = (1 << 63) - 1

__all__ = ["FileStoreCommit", "CommitConflictError", "CommitGiveUpError"]


class CommitConflictError(RuntimeError):
    pass


class CommitGiveUpError(RuntimeError):
    """The bounded commit retry loop (commit.max-retries) was exhausted
    without winning the snapshot CAS. The table is untouched by this commit
    (every round's metadata was cleaned up); the committable may be replayed."""


class FileStoreCommit:
    def __init__(
        self,
        file_io: FileIO,
        table_path: str,
        commit_user: str,
        schema_id: int,
        options: CoreOptions | None = None,
        cache=None,
    ):
        self.file_io = file_io
        self.table_path = table_path
        self.commit_user = commit_user
        self.schema_id = schema_id
        self.options = options or CoreOptions()
        # external mutual exclusion where the FS rename is not atomic
        # (reference: commits run under CatalogLock on such stores)
        self._lock = None
        if self.options.options.get(CoreOptions.COMMIT_CATALOG_LOCK) or not getattr(
            file_io, "atomic_write_supported", True
        ):
            lock_type = self.options.options.get(CoreOptions.COMMIT_CATALOG_LOCK_TYPE)
            timeout = self.options.options.get(CoreOptions.COMMIT_CATALOG_LOCK_TIMEOUT)
            stale_ttl = self.options.options.get(CoreOptions.COMMIT_CATALOG_LOCK_STALE_TTL)
            if lock_type == "jdbc":
                from ..catalog.jdbc import JdbcCatalogLock

                db = self.options.options.get(CoreOptions.COMMIT_CATALOG_LOCK_JDBC_PATH)
                if not db:
                    raise ValueError("commit.catalog-lock.type=jdbc needs commit.catalog-lock.jdbc-path")
                self._lock = JdbcCatalogLock(db, lock_id=table_path, timeout=timeout, stale_ttl=stale_ttl)
            elif lock_type == "file":
                if not getattr(file_io, "exclusive_create_supported", True):
                    # a file lock on a store without exclusive create is
                    # check-then-put theater: two holders would both "win"
                    raise ValueError(
                        "this store has no exclusive create (no conditional PUT); "
                        "the file-based catalog lock cannot provide mutual exclusion — "
                        "configure commit.catalog-lock.type=jdbc with "
                        "commit.catalog-lock.jdbc-path"
                    )
                from ..catalog.lock import FileBasedCatalogLock

                self._lock = FileBasedCatalogLock(file_io, table_path, timeout=timeout, stale_ttl=stale_ttl)
            else:
                raise ValueError(f"unknown commit.catalog-lock.type: {lock_type!r} (expected 'file' or 'jdbc')")
        # manifest object cache: every commit re-reads the latest snapshot's
        # base+delta manifests (conflict check, manifest merge) — immutable
        # files, so the decoded entries come from the shared cache
        self.snapshot_manager = SnapshotManager(file_io, table_path, cache=cache)
        self.manifest_file = ManifestFile(file_io, f"{table_path}/manifest", cache=cache)
        self.manifest_list = ManifestList(file_io, f"{table_path}/manifest", cache=cache)

    # ---- idempotence ----------------------------------------------------
    def filter_committed(self, committables: Sequence[ManifestCommittable]) -> list[ManifestCommittable]:
        """Drop committables whose identifier this user already committed
        (crash-replay safety; reference FileStoreCommit.filterCommitted).

        Only streaming committables route through here (batch commits carry
        the sentinel identifier and skip the filter), so the watermark is the
        user's latest NON-sentinel snapshot: a batch maintenance commit by
        the same user must not make every pending streaming identifier look
        already-committed (the reference avoids this only by convention —
        fresh UUID commit users per job)."""
        latest_of_user = None
        for snap in self.snapshot_manager.snapshots_of_user(self.commit_user):
            if snap.commit_identifier != BATCH_COMMIT_IDENTIFIER:
                latest_of_user = snap
                break
        if latest_of_user is None:
            return list(committables)
        done = latest_of_user.commit_identifier
        out: list[ManifestCommittable] = []
        for c in committables:
            if c.commit_identifier > done:
                out.append(c)
            elif c.commit_identifier == done:
                # the APPEND snapshot landed; keep the committable (flagged to
                # skip its APPEND phase) if its COMPACT half is still missing
                has_compact = any(m.compact_before or m.compact_after for m in c.messages)
                if has_compact:
                    kinds = {
                        s.commit_kind
                        for s in self.snapshot_manager.snapshots_of_user_with_identifier(
                            self.commit_user, c.commit_identifier
                        )
                    }
                    if CommitKind.COMPACT not in kinds:
                        out.append(replace(c, skip_append=True))
        return out

    # ---- commit ---------------------------------------------------------
    def commit(self, committable: ManifestCommittable) -> list[int]:
        """Returns the snapshot ids written (0, 1, or 2)."""
        append_entries: list[ManifestEntry] = []
        compact_entries: list[ManifestEntry] = []
        append_changelog: list[ManifestEntry] = []
        compact_changelog: list[ManifestEntry] = []
        for msg in committable.messages:
            for f in msg.new_files:
                append_entries.append(ManifestEntry(FileKind.ADD, msg.partition, msg.bucket, msg.total_buckets, f))
            for f in msg.compact_before:
                compact_entries.append(ManifestEntry(FileKind.DELETE, msg.partition, msg.bucket, msg.total_buckets, f))
            for f in msg.compact_after:
                compact_entries.append(ManifestEntry(FileKind.ADD, msg.partition, msg.bucket, msg.total_buckets, f))
            for f in msg.changelog_files:
                append_changelog.append(ManifestEntry(FileKind.ADD, msg.partition, msg.bucket, msg.total_buckets, f))
            for f in msg.compact_changelog_files:
                compact_changelog.append(ManifestEntry(FileKind.ADD, msg.partition, msg.bucket, msg.total_buckets, f))
        index_entries = [e for msg in committable.messages for e in msg.new_index_files]
        written: list[int] = []
        if not committable.skip_append and (
            append_entries or index_entries or append_changelog or not compact_entries
        ):
            written.append(
                self._try_commit(
                    CommitKind.APPEND,
                    append_entries,
                    committable,
                    check_conflicts=False,
                    index_entries=index_entries,
                    changelog_entries=append_changelog,
                )
            )
            # from here the APPEND snapshot is durable: flag the committable so
            # a caller retrying it (or replaying via filter_committed) cannot
            # double-apply the APPEND phase if COMPACT fails below
            committable.skip_append = True
        if compact_entries:
            # purge DVs only for files that truly disappear: an upgrade emits
            # DELETE+ADD with the SAME file name (level change only) and its
            # DV must survive
            added_names = {e.file.file_name for e in compact_entries if e.kind == FileKind.ADD}
            removed = [
                e
                for e in compact_entries
                if e.kind == FileKind.DELETE and e.file.file_name not in added_names
            ]
            written.append(
                self._try_commit(
                    CommitKind.COMPACT,
                    compact_entries,
                    committable,
                    check_conflicts=True,
                    removed_files=removed,
                    changelog_entries=compact_changelog,
                )
            )
        return [w for w in written if w >= 0]

    def overwrite(
        self,
        committable: ManifestCommittable,
        partition_filter: Callable[[tuple], bool] | None = None,
    ) -> list[int]:
        """INSERT OVERWRITE: logically delete current files (of the matching
        partitions), then add the new ones, in one OVERWRITE snapshot."""
        latest = self.snapshot_manager.latest_snapshot()
        entries: list[ManifestEntry] = []
        if latest is not None:
            for e in self._live_entries(latest):
                if partition_filter is None or partition_filter(e.partition):
                    entries.append(ManifestEntry(FileKind.DELETE, e.partition, e.bucket, e.total_buckets, e.file))
        for msg in committable.messages:
            for f in msg.new_files:
                entries.append(ManifestEntry(FileKind.ADD, msg.partition, msg.bucket, msg.total_buckets, f))
        return [self._try_commit(CommitKind.OVERWRITE, entries, committable, check_conflicts=False)]

    # ---- internals ------------------------------------------------------
    def _live_entries(self, snapshot: Snapshot) -> list[ManifestEntry]:
        metas = self.manifest_list.read(snapshot.base_manifest_list) + self.manifest_list.read(
            snapshot.delta_manifest_list
        )
        return merge_entries(*(self.manifest_file.read(m.file_name) for m in metas))

    def _index_manifest(
        self, latest: Snapshot | None, index_entries: list, removed_files: list[ManifestEntry] | None = None
    ) -> str | None:
        """New index manifest = previous entries with same-(partition, bucket,
        kind) slots replaced by this commit's entries (a maintainer always
        emits the complete replacement set for its bucket). For commits that
        remove data files (COMPACT/OVERWRITE), deletion vectors of the dead
        files are purged — their rows were physically dropped during the
        rewrite, and keeping stale DVs would desynchronize the index."""
        from .deletionvectors import DeletionVectorsIndexFile
        from .indexmanifest import read_index_manifest, write_index_manifest

        prev: list = []
        if latest is not None and latest.index_manifest:
            prev = read_index_manifest(self.file_io, self.table_path, latest.index_manifest)
        dead_by_pb: dict[tuple, set] = {}
        for e in removed_files or []:
            dead_by_pb.setdefault((e.partition, e.bucket), set()).add(e.file.file_name)
        if not index_entries and not dead_by_pb:
            return latest.index_manifest if latest else None
        replaced = {(e.partition, e.bucket, e.kind) for e in index_entries}
        out = []
        dv_io = DeletionVectorsIndexFile(
            self.file_io,
            self.table_path,
            target_size=int(
                self.options.options.get(CoreOptions.DELETION_VECTOR_INDEX_FILE_TARGET_SIZE)
            ),
        )
        for e in prev:
            if (e.partition, e.bucket, e.kind) in replaced:
                continue
            dead = dead_by_pb.get((e.partition, e.bucket))
            if dead and e.kind == "DELETION_VECTORS":
                dvs = dv_io.read_all(e.file_name)
                live = {f: dv for f, dv in dvs.items() if f not in dead}
                if not live:
                    continue
                if len(live) != len(dvs):
                    name, total = dv_io.write(live)
                    from .deletionvectors import IndexFileEntry

                    e = IndexFileEntry(e.kind, e.partition, e.bucket, name, total)
            out.append(e)
        out.extend(index_entries)
        if not out:
            return None
        return write_index_manifest(self.file_io, self.table_path, out)

    def _try_commit(
        self,
        kind: CommitKind,
        entries: list[ManifestEntry],
        committable: ManifestCommittable,
        check_conflicts: bool,
        index_entries: list | None = None,
        removed_files: list[ManifestEntry] | None = None,
        changelog_entries: list[ManifestEntry] | None = None,
        statistics: str | None = None,
    ) -> int:
        import random
        import time

        from ..metrics import registry

        g = registry.group("commit")
        opts = self.options.options
        max_retries = opts.get(CoreOptions.COMMIT_MAX_RETRIES)
        backoff_base = float(opts.get(CoreOptions.COMMIT_RETRY_BACKOFF))
        prev_backoff: float | None = None
        retries = 0
        t_start = time.perf_counter()
        from contextlib import nullcontext

        while True:
            with self._lock.lock() if self._lock is not None else nullcontext():
                latest = self.snapshot_manager.latest_snapshot()
                if check_conflicts and latest is not None:
                    conflicted = self._conflicted_buckets(latest, entries)
                    if conflicted:
                        g.counter("conflicts").inc()
                        all_buckets = {(e.partition, e.bucket) for e in entries}
                        if all_buckets <= conflicted:
                            raise CommitConflictError(
                                f"files of bucket(s) {sorted(conflicted)} were removed by a "
                                f"concurrent commit; giving up this {kind.value} commit"
                            )
                        # retriable conflict: only SOME buckets lost their
                        # inputs to a concurrent commit. Abandon those (their
                        # rewritten outputs become orphans, reclaimed by
                        # remove_orphan_files) and re-plan the untouched
                        # buckets against the new latest — finer-grained than
                        # the seed's whole-commit abort.
                        g.counter("buckets_abandoned").inc(len(conflicted))
                        entries = [e for e in entries if (e.partition, e.bucket) not in conflicted]
                        removed_files = [
                            e for e in (removed_files or []) if (e.partition, e.bucket) not in conflicted
                        ]
                        changelog_entries = [
                            e for e in (changelog_entries or []) if (e.partition, e.bucket) not in conflicted
                        ]
                        index_entries = [
                            ie for ie in (index_entries or []) if (ie.partition, ie.bucket) not in conflicted
                        ]
                crash_point("commit:before-manifests")
                tmp_files: list[str] = []
                try:
                    snapshot_id = (latest.id + 1) if latest else 1
                    base_metas = (
                        self.manifest_list.read(latest.base_manifest_list)
                        + self.manifest_list.read(latest.delta_manifest_list)
                        if latest
                        else []
                    )
                    base_metas = self._maybe_merge_manifests(base_metas, tmp_files)
                    delta_meta = self.manifest_file.write(entries, self.schema_id, track=tmp_files)
                    base_name = self.manifest_list.write(base_metas, track=tmp_files)
                    delta_name = self.manifest_list.write([delta_meta], track=tmp_files)
                    changelog_list = None
                    changelog_rows = None
                    if changelog_entries:
                        cl_meta = self.manifest_file.write(changelog_entries, self.schema_id, track=tmp_files)
                        changelog_list = self.manifest_list.write([cl_meta], track=tmp_files)
                        changelog_rows = sum(e.file.row_count for e in changelog_entries)
                    added = sum(e.file.row_count for e in entries if e.kind == FileKind.ADD)
                    deleted = sum(e.file.row_count for e in entries if e.kind == FileKind.DELETE)
                    prev_total = (latest.total_record_count or 0) if latest else 0
                    index_manifest = self._index_manifest(latest, index_entries or [], removed_files)
                    if index_manifest and index_manifest != (latest.index_manifest if latest else None):
                        # freshly written this round: clean it up with the
                        # other metadata if the CAS is lost/aborted (the seed
                        # leaked it)
                        tmp_files.append(index_manifest)
                    snapshot = Snapshot(
                        id=snapshot_id,
                        schema_id=self.schema_id,
                        base_manifest_list=base_name,
                        delta_manifest_list=delta_name,
                        changelog_manifest_list=changelog_list,
                        commit_user=self.commit_user,
                        commit_identifier=committable.commit_identifier,
                        commit_kind=kind,
                        time_millis=now_millis(),
                        index_manifest=index_manifest,
                        total_record_count=prev_total + added - deleted,
                        delta_record_count=added - deleted,
                        changelog_record_count=changelog_rows,
                        statistics=statistics,
                        watermark=committable.watermark,
                        log_offsets=dict(committable.log_offsets),
                    )
                    crash_point("commit:manifests-written")
                    path = self.snapshot_manager.snapshot_path(snapshot_id)
                    if self.file_io.try_atomic_write(path, snapshot.to_json().encode()):
                        g.counter("commits").inc()
                        g.counter("retries").inc(retries)
                        g.histogram("duration_ms").update((time.perf_counter() - t_start) * 1000)
                        # committed: the snapshot now references these manifests —
                        # they must never be cleaned up, even if hints fail
                        tmp_files.clear()
                        crash_point("commit:snapshot-committed")
                        try:
                            self.snapshot_manager.commit_latest_hint(snapshot_id)
                            if snapshot_id == 1:
                                self.snapshot_manager.commit_earliest_hint(1)
                        except Exception:
                            pass  # hints are best-effort; listing is authoritative
                        return snapshot_id
                    # lost the CAS race. First: did OUR commit actually land?
                    # (an IO-layer retry of a rename whose ack was lost, or a
                    # replay racing its own earlier attempt) — adopting it
                    # instead of re-committing prevents double-apply.
                    own = self._find_own_commit(snapshot_id, committable, kind, delta_name)
                    if own is not None:
                        self._cleanup_after_adopt(own, tmp_files)
                        return own
                    # genuinely lost to another committer: clean this round's
                    # metadata and retry against the new latest
                    self._cleanup(tmp_files)
                    retries += 1
                    if retries > max_retries:
                        raise CommitGiveUpError(
                            f"commit lost the snapshot race {retries} times "
                            f"(commit.max-retries={max_retries}); giving up"
                        )
                except Exception:
                    # an exception may have escaped mid-write, so this is the
                    # one path where torn tmp siblings can exist
                    self._cleanup(tmp_files, sweep_torn=True)
                    raise
                # a simulated CrashError (BaseException) bypasses the cleanup
                # above on purpose: a killed process runs no cleanup either —
                # recovery is remove_orphan_files' job
            # backoff OUTSIDE the lock so racing committers make progress;
            # decorrelated jitter desynchronizes the herd
            if backoff_base > 0:
                hi = min(backoff_base * 100.0, max(backoff_base, (prev_backoff or backoff_base) * 3.0))
                prev_backoff = random.uniform(backoff_base, hi)
                time.sleep(prev_backoff / 1000.0)

    def _conflicted_buckets(self, latest: Snapshot, entries: list[ManifestEntry]) -> set[tuple]:
        """(partition, bucket) slots whose logically-deleted files are no
        longer live (reference noConflictsOrFail :804-808 — a concurrent
        compaction removing the same files is a conflict; the loser abandons
        that bucket's compaction)."""
        deletes = [e for e in entries if e.kind == FileKind.DELETE]
        if not deletes:
            return set()
        live = {(e.partition, e.bucket, e.file.file_name) for e in self._live_entries(latest)}
        return {
            (e.partition, e.bucket)
            for e in deletes
            if (e.partition, e.bucket, e.file.file_name) not in live
        }

    def _find_own_commit(
        self, from_id: int, committable: ManifestCommittable, kind: CommitKind, delta_name: str
    ) -> int | None:
        """After a lost CAS at `from_id`: the id of an already-landed snapshot
        that is OURS, or None. Two proofs of ownership:

        - content: the snapshot at `from_id` references the uuid-named delta
          manifest list written THIS round — only our own rename (whose ack
          was lost and whose IO-layer retry then saw `path exists` → False)
          can have published those bytes. This also covers batch/maintenance
          commits, whose sentinel identifier proves nothing.
        - identity: a snapshot carrying our (user, identifier, kind) — covers
          a crash-replay racing its own earlier attempt, which wrote its own
          manifest copies. Sentinel identifiers are shared across logical
          commits and are excluded from this scan.
        """
        if self.snapshot_manager.snapshot_exists(from_id):
            try:
                snap = self.snapshot_manager.snapshot(from_id)
            except Exception:
                snap = None  # racing expiry etc.; fall through to identity
            if snap is not None and snap.delta_manifest_list == delta_name:
                return from_id
        ident = committable.commit_identifier
        if ident >= BATCH_COMMIT_IDENTIFIER - 16:
            return None
        latest_id = self.snapshot_manager.latest_snapshot_id()
        if latest_id is None:
            return None
        for sid in range(from_id, latest_id + 1):
            if not self.snapshot_manager.snapshot_exists(sid):
                continue
            snap = self.snapshot_manager.snapshot(sid)
            if (
                snap.commit_user == self.commit_user
                and snap.commit_identifier == ident
                and snap.commit_kind == kind
            ):
                return sid
        return None

    def _cleanup_after_adopt(self, own_id: int, tmp_files: list[str]) -> None:
        """Cleanup after adopting an already-landed snapshot as our own. In
        the lost-rename-ack case the adopted snapshot IS this round's bytes:
        every manifest it references is live and must survive cleanup, or the
        latest snapshot dangles and the table is unreadable. A rival replay
        wrote its own manifest copies, so nothing intersects and this round's
        metadata is swept as usual. If the adopted snapshot cannot be re-read
        we leak rather than delete: the orphan sweep reclaims true orphans
        later, while a wrong delete here is unrecoverable."""
        try:
            snap = self.snapshot_manager.snapshot(own_id)
            live = {
                n
                for n in (
                    snap.base_manifest_list,
                    snap.delta_manifest_list,
                    snap.changelog_manifest_list,
                    snap.index_manifest,
                )
                if n
            }
            for lst in (
                snap.base_manifest_list,
                snap.delta_manifest_list,
                snap.changelog_manifest_list,
            ):
                if lst:
                    live.update(m.file_name for m in self.manifest_list.read(lst))
        except Exception:
            tmp_files.clear()
            return
        tmp_files[:] = [n for n in tmp_files if n not in live]
        self._cleanup(tmp_files)

    def _maybe_merge_manifests(
        self, metas: list[ManifestFileMeta], tmp_files: list[str]
    ) -> list[ManifestFileMeta]:
        """Compact many small manifests into fewer big ones (reference
        ManifestFileMeta.merge at commit :843-852). Two triggers:
        - count: >= manifest.merge-min-count small manifests merge together
          (DELETE entries survive — older manifests may still reference them)
        - size (full compaction, reference manifest.full-compaction-threshold-size):
          once the small/unmerged manifests exceed the threshold bytes, ALL
          manifests rewrite into fresh compacted ones; with the whole history
          merged, DELETE entries resolve away entirely."""
        min_count = self.options.options.get(CoreOptions.MANIFEST_MERGE_MIN_COUNT)
        target = int(self.options.options.get(CoreOptions.MANIFEST_TARGET_SIZE))
        full_threshold = int(
            self.options.options.get(CoreOptions.MANIFEST_FULL_COMPACTION_THRESHOLD_SIZE)
        )
        small = [m for m in metas if m.file_size < target]
        total_bytes = sum(m.file_size for m in metas)
        # convergence guard: a full compaction's own output is ~ideal_chunks
        # manifests; only re-trigger when the history is genuinely fragmented
        # beyond that, or every commit would rewrite everything (quadratic)
        ideal_chunks = max(1, -(-total_bytes // target))
        fragmented = len(metas) > 2 * ideal_chunks
        if small and fragmented and sum(m.file_size for m in small) >= full_threshold:
            entries = merge_entries(*(self.manifest_file.read(m.file_name) for m in metas))
            out, small, big = [], [], []  # rewrite everything below
        elif len(small) < min_count:
            return metas
        else:
            big = [m for m in metas if m.file_size >= target]
            entries = merge_entries_keep_deletes(*(self.manifest_file.read(m.file_name) for m in small))
            out = list(big)
        if entries:
            # chunk to target size with an ADAPTIVE bytes/entry estimate:
            # after each write the measured size corrects the next chunk, so
            # outputs land near target regardless of compression ratio
            per_entry = 400.0
            i = 0
            while i < len(entries):
                per_file = max(1, int(target / per_entry))
                chunk = entries[i : i + per_file]
                meta = self.manifest_file.write(chunk, self.schema_id, track=tmp_files)
                out.append(meta)
                per_entry = max(1.0, meta.file_size / max(len(chunk), 1))
                i += len(chunk)
        return out

    def _cleanup(self, names: list[str], sweep_torn: bool = False) -> None:
        """Best-effort removal of this round's metadata after an abort or a
        lost CAS race: the tracked manifest names and — only when `sweep_torn`
        — their torn `.tmp.*` siblings (an atomic write that failed between
        tmp write and rename leaves one; names are tracked BEFORE any byte is
        written, so even a write that died mid-flight is covered). A lost-CAS
        round completed every write, and a completed try_atomic_write leaves
        no torn sibling, so those rounds skip the directory LIST entirely (an
        object-store LIST per retry round is real money). Failures are
        non-fatal (the original error must win; leftovers become orphans for
        remove_orphan_files) and are counted in io{cleanup_failures} — except
        a missing manifest dir, which just means the round died before its
        first byte landed."""
        if not names:
            return
        from ..metrics import io_metrics

        g = io_metrics()
        mdir = f"{self.table_path}/manifest"
        siblings: dict[str, list[str]] = {}
        if sweep_torn:
            try:
                for st in self.file_io.list_files(mdir):
                    base = st.path.rsplit("/", 1)[-1]
                    if base.startswith(".") and base.endswith(".tmp"):
                        # .<name>.<hex>.tmp -> <name>; only OUR tracked names are
                        # swept (a concurrent committer's in-flight tmp must live).
                        # Path rebuilt from mdir: wrapper FileIOs list inner paths.
                        siblings.setdefault(base[1:].rsplit(".", 2)[0], []).append(f"{mdir}/{base}")
            except FileNotFoundError:
                pass  # dir never created: nothing to sweep
            except Exception:
                g.counter("cleanup_failures").inc()
        for name in names:
            for target in (f"{mdir}/{name}", *siblings.get(name, ())):
                try:
                    self.file_io.delete(target)
                except Exception:
                    g.counter("cleanup_failures").inc()
        names.clear()
