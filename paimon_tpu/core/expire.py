"""Snapshot expiration: retention windows + safe physical deletion.

Parity: /root/reference/paimon-core/.../operation/ExpireSnapshotsImpl +
SnapshotDeletion — expire snapshots outside (num-retained-min/max,
time-retained), then delete data files and manifests referenced only by the
expired snapshots. Protected snapshots (tags, consumers) are excluded via the
`protected_ids` provider.
"""

from __future__ import annotations

from typing import Callable, Iterable

from ..fs import FileIO
from ..options import CoreOptions
from ..utils import now_millis
from .manifest import ManifestFile, ManifestList, merge_entries
from .snapshot import Snapshot, SnapshotManager

__all__ = ["SnapshotExpire"]


class SnapshotExpire:
    def __init__(
        self,
        file_io: FileIO,
        table_path: str,
        options: CoreOptions | None = None,
        protected_ids: Callable[[], Iterable[int]] | None = None,
        partition_keys: Iterable[str] = (),
    ):
        self._partition_keys = tuple(partition_keys)
        self.file_io = file_io
        self.table_path = table_path
        self.options = options or CoreOptions()
        # reads go through the shared manifest cache (scan populated most of
        # these already); deletions below invalidate via the global helpers
        # so every cached variant dies with the file, whoever cached it
        from ..utils.cache import table_caches

        cache, _ = table_caches(self.options)
        self.snapshot_manager = SnapshotManager(file_io, table_path, cache=cache)
        self.manifest_file = ManifestFile(file_io, f"{table_path}/manifest", cache=cache)
        self.manifest_list = ManifestList(file_io, f"{table_path}/manifest", cache=cache)
        self.protected_ids = protected_ids or (lambda: ())

    def _safe_delete(self, path: str) -> bool:
        """Physical deletion during expiry is best-effort: a transient store
        fault on one file must not abort the run half-way (leaving SOME
        snapshots deleted and their files still referenced-looking). A failed
        delete leaves an unreachable file — exactly what remove_orphan_files
        reclaims on its next sweep — and counts in io{cleanup_failures}."""
        try:
            self.file_io.delete(path)
            return True
        except Exception:
            from ..metrics import io_metrics

            io_metrics().counter("cleanup_failures").inc()
            return False

    def _changelog_decoupled(self) -> bool:
        return any(
            self.options.options.get(o) is not None
            for o in (
                CoreOptions.CHANGELOG_NUM_RETAINED_MIN,
                CoreOptions.CHANGELOG_NUM_RETAINED_MAX,
                CoreOptions.CHANGELOG_TIME_RETAINED,
            )
        )

    def expire(self) -> int:
        n = self._expire_snapshots()
        # changelog retention is independent of snapshot expiry: aged
        # changelogs must trim even in runs where no snapshot is expirable
        if self._changelog_decoupled():
            self.expire_changelogs()
        return n

    def _expire_snapshots(self) -> int:
        sm = self.snapshot_manager
        latest = sm.latest_snapshot_id()
        earliest = sm.earliest_snapshot_id()
        if latest is None or earliest is None:
            return 0
        retained_min = self.options.snapshot_num_retained_min
        retained_max = self.options.snapshot_num_retained_max
        time_retained = self.options.snapshot_time_retained_ms
        # the newest id that may be expired (exclusive end of expiry range)
        end = max(earliest, latest - retained_max + 1)
        # time rule can push further, bounded by retained_min
        time_bound = max(earliest, latest - retained_min + 1)
        cutoff = now_millis() - time_retained
        for sid in range(end, time_bound):
            if sm.snapshot_exists(sid) and sm.snapshot(sid).time_millis < cutoff:
                end = sid + 1
            else:
                break
        # bound work per run (reference ExpireConfig snapshot max deletes)
        limit = self.options.options.get(CoreOptions.SNAPSHOT_EXPIRE_LIMIT)
        if limit is not None and end - earliest > limit:
            end = earliest + limit
        protected = set(self.protected_ids())
        expire_ids = [i for i in range(earliest, end) if i not in protected and sm.snapshot_exists(i)]
        if not expire_ids:
            return 0
        retained_ids = [i for i in range(earliest, latest + 1) if i not in expire_ids and sm.snapshot_exists(i)]

        live_files: set[tuple] = set()
        live_manifests: set[str] = set()
        for sid in retained_ids:
            snap = sm.snapshot(sid)
            for name, entries in self._snapshot_manifests(snap):
                live_manifests.add(name)
                for e in entries:
                    live_files.add((e.partition, e.bucket, e.file.file_name))
            live_manifests.add(snap.base_manifest_list)
            live_manifests.add(snap.delta_manifest_list)
            if snap.changelog_manifest_list:
                live_manifests.add(snap.changelog_manifest_list)

        # decoupled changelog lifecycle (reference Changelog.java +
        # ChangelogDeletion): with changelog retention configured, an
        # expiring snapshot that carries changelog leaves a changelog-<id>
        # copy behind and its changelog manifests/files survive the snapshot
        decoupled = self._changelog_decoupled()
        dead_manifests: set[str] = set()
        dead_files: set[tuple] = set()
        for sid in expire_ids:
            snap = sm.snapshot(sid)
            preserve_changelog = decoupled and snap.changelog_manifest_list
            if preserve_changelog:
                self.file_io.write_bytes(
                    sm.changelog_path(sid), snap.to_json().encode(), overwrite=True
                )
            for name, entries in self._snapshot_manifests(snap, include_changelog=not preserve_changelog):
                if name not in live_manifests:
                    dead_manifests.add(name)
                for e in entries:
                    key = (e.partition, e.bucket, e.file.file_name)
                    if key not in live_files:
                        dead_files.add((key, e.file.extra_files))
            dead_lists = [snap.base_manifest_list, snap.delta_manifest_list]
            if not preserve_changelog:
                dead_lists.append(snap.changelog_manifest_list)
            for lst in dead_lists:
                if lst and lst not in live_manifests:
                    dead_manifests.add(lst)

        from ..utils import partition_path
        from ..utils.cache import invalidate_data_file, invalidate_manifest_path, invalidate_snapshot

        touched_dirs: set[str] = set()
        for (partition, bucket, file_name), extra in dead_files:
            # partition path needs key names; data dirs embed them already —
            # bucket dirs are resolved by the store layer convention
            pp = self._bucket_dir(partition, bucket)
            touched_dirs.add(pp)
            self._safe_delete(f"{pp}/{file_name}")
            invalidate_data_file(file_name)
            for x in extra:
                self._safe_delete(f"{pp}/{x}")
        for name in dead_manifests:
            self._safe_delete(f"{self.table_path}/manifest/{name}")
            invalidate_manifest_path(f"{self.table_path}/manifest/{name}")
        for sid in expire_ids:
            self._safe_delete(sm.snapshot_path(sid))
            invalidate_snapshot(self.table_path, sid)
        # the hint must point at the smallest SURVIVING snapshot: protected
        # (tag/consumer) snapshots inside the expired range stay on disk, and
        # walks that trust the hint (earliest_snapshot_id, user scans) would
        # otherwise never see them again once unprotected
        sm.commit_earliest_hint(min(retained_ids))
        if self.options.options.get(CoreOptions.SNAPSHOT_EXPIRE_CLEAN_EMPTY_DIRS):
            # sweep bucket dirs emptied by this run, then their parent
            # partition dirs — AFTER every metadata deletion (the sweep is
            # cosmetic; a concurrent writer repopulating a dir between the
            # emptiness check and the rmdir must never abort expiry)
            for d in sorted(touched_dirs, key=len, reverse=True):
                try:
                    if not self.file_io.list_status(d):
                        self.file_io.delete(d)
                        parent = d.rsplit("/", 1)[0]
                        while parent != self.table_path and not self.file_io.list_status(parent):
                            self.file_io.delete(parent)
                            parent = parent.rsplit("/", 1)[0]
                except OSError:
                    continue  # dir went live again: leave it
        return len(expire_ids)

    def expire_changelogs(self) -> int:
        """Expire decoupled changelogs by changelog.num-retained.min/max and
        changelog.time-retained; consumer/tag-protected ids stay (reference
        ChangelogDeletion). Changelog data files are per-snapshot, never
        shared, so they die with their changelog."""
        from ..utils import now_millis

        sm = self.snapshot_manager
        ids = sm.changelog_ids()
        if not ids:
            return 0
        opts = self.options.options
        min_r = opts.get(CoreOptions.CHANGELOG_NUM_RETAINED_MIN) or 0
        max_r = opts.get(CoreOptions.CHANGELOG_NUM_RETAINED_MAX)
        ttl = opts.get(CoreOptions.CHANGELOG_TIME_RETAINED)
        protected = set(self.protected_ids())
        expire: list[int] = []
        if max_r is not None and len(ids) > max_r:
            expire.extend(ids[: len(ids) - max_r])
        rest = ids[len(expire) :]
        if ttl is not None:
            cutoff = now_millis() - ttl
            for cid in rest[: max(0, len(rest) - min_r)]:
                if sm.changelog(cid).time_millis < cutoff:
                    expire.append(cid)
                else:
                    break
        from ..utils.cache import invalidate_data_file, invalidate_manifest_path

        n = 0
        for cid in expire:
            if cid in protected:
                continue
            snap = sm.changelog(cid)
            if snap.changelog_manifest_list:
                for meta in self.manifest_list.read(snap.changelog_manifest_list):
                    for e in self.manifest_file.read(meta.file_name):
                        d = self._bucket_dir(e.partition, e.bucket)
                        self._safe_delete(f"{d}/{e.file.file_name}")
                        invalidate_data_file(e.file.file_name)
                        for x in e.file.extra_files:
                            self._safe_delete(f"{d}/{x}")
                    self._safe_delete(f"{self.table_path}/manifest/{meta.file_name}")
                    invalidate_manifest_path(f"{self.table_path}/manifest/{meta.file_name}")
                self._safe_delete(f"{self.table_path}/manifest/{snap.changelog_manifest_list}")
                invalidate_manifest_path(f"{self.table_path}/manifest/{snap.changelog_manifest_list}")
            self._safe_delete(sm.changelog_path(cid))
            n += 1
        return n

    def _snapshot_manifests(self, snap: Snapshot, include_changelog: bool = True):
        # changelog manifests included by default: their manifest files AND
        # the changelog data files they reference die with the snapshot
        # (reference SnapshotDeletion) — unless the decoupled lifecycle is
        # preserving them past snapshot expiry
        lists = [snap.base_manifest_list, snap.delta_manifest_list]
        if include_changelog:
            lists.append(snap.changelog_manifest_list)
        for lst in lists:
            if not lst:
                continue
            for meta in self.manifest_list.read(lst):
                yield meta.file_name, self.manifest_file.read(meta.file_name)

    def _bucket_dir(self, partition: tuple, bucket: int) -> str:
        from ..utils import partition_path

        pp = partition_path(
            self._partition_keys,
            partition,
            default_name=self.options.options.get(CoreOptions.PARTITION_DEFAULT_NAME),
        )
        base = f"{self.table_path}/{pp}" if pp else self.table_path
        return f"{base}/bucket-{bucket}"
