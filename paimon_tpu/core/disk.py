"""Spill infrastructure: host-memory pressure relief for write buffers.

Parity: /root/reference/paimon-core/.../disk/ — IOManagerImpl (temp spill
dirs + file channels) and ExternalBuffer/RowBuffer (the spillable row buffer
behind AppendOnlyWriter and local merge; the keyed path's analog is
BinaryExternalSortBuffer). Batches spill as arrow IPC streams (fast,
zero-schema-loss) and read back lazily at flush.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import uuid
from typing import Iterator

from ..data.batch import ColumnBatch

__all__ = ["IOManager", "SpillableBuffer"]


class IOManager:
    """Owns a temp spill directory tree (reference disk/IOManagerImpl)."""

    def __init__(self, base_dir: str | None = None):
        self.base = base_dir or tempfile.mkdtemp(prefix="paimon_tpu_spill_")
        os.makedirs(self.base, exist_ok=True)

    def create_channel(self) -> str:
        return os.path.join(self.base, f"spill-{uuid.uuid4().hex}.arrow")

    def close(self) -> None:
        shutil.rmtree(self.base, ignore_errors=True)


class SpillableBuffer:
    """Buffers ColumnBatches in memory; beyond `in_memory_rows` they spill to
    arrow IPC files. Iteration replays spilled segments then memory, in
    insertion order (reference ExternalBuffer semantics)."""

    def __init__(
        self,
        io_manager: IOManager,
        in_memory_rows: int = 1 << 20,
        in_memory_bytes: int = 64 << 20,
        max_disk_bytes: int | None = None,
    ):
        self.io_manager = io_manager
        self.in_memory_rows = in_memory_rows
        self.in_memory_bytes = in_memory_bytes
        # write-buffer-spill.max-disk-size: past this, add() stops spilling
        # (disk_full flips True) so the owner flushes instead
        self.max_disk_bytes = max_disk_bytes
        self._memory: list[ColumnBatch] = []
        self._memory_rows = 0
        self._memory_bytes = 0
        self._spilled: list[str] = []
        self._spilled_rows = 0
        self._spilled_disk_bytes = 0

    @property
    def disk_full(self) -> bool:
        return self.max_disk_bytes is not None and self._spilled_disk_bytes >= self.max_disk_bytes

    @property
    def num_rows(self) -> int:
        return self._memory_rows + self._spilled_rows

    @property
    def spilled_bytes(self) -> int:
        return sum(os.path.getsize(p) for p in self._spilled if os.path.exists(p))

    def add(self, batch: ColumnBatch) -> None:
        if batch.num_rows == 0:
            return
        self._memory.append(batch)
        self._memory_rows += batch.num_rows
        self._memory_bytes += batch.byte_size()
        if (
            self._memory_rows > self.in_memory_rows or self._memory_bytes > self.in_memory_bytes
        ) and not self.disk_full:
            self._spill()

    def _spill(self) -> None:
        import pyarrow as pa

        path = self.io_manager.create_channel()
        first = self._memory[0].to_arrow()
        with pa.OSFile(path, "wb") as sink:
            with pa.ipc.new_stream(sink, first.schema) as writer:
                writer.write_table(first)
                for b in self._memory[1:]:
                    writer.write_table(b.to_arrow())
        # remember the logical schema to rebuild batches on read
        self._spilled.append(path)
        self._schema = self._memory[0].schema
        self._spilled_rows += self._memory_rows
        self._spilled_disk_bytes += os.path.getsize(path)
        self._memory.clear()
        self._memory_bytes = 0
        self._memory_rows = 0

    def batches(self) -> Iterator[ColumnBatch]:
        import pyarrow as pa

        for path in self._spilled:
            with pa.OSFile(path, "rb") as f:
                reader = pa.ipc.open_stream(f)
                table = reader.read_all()
            yield ColumnBatch.from_arrow(table, self._schema)
        yield from self._memory

    def clear(self) -> None:
        for p in self._spilled:
            try:
                os.remove(p)
            except OSError:
                pass
        self._spilled.clear()
        self._spilled_rows = 0
        self._spilled_disk_bytes = 0
        self._memory.clear()
        self._memory_rows = 0
        self._memory_bytes = 0
