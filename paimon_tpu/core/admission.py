"""Writer admission control: a byte budget over buffered memtables.

The delta/main architecture ("Fast Updates on Read-Optimized Databases",
PAPERS.md) assumes the delta never outruns the merge that drains it. Under
sustained concurrent ingest that assumption needs enforcement: every
memtable byte a writer buffers — and every byte still being encoded by the
PR-4 offloaded flush worker — is host memory that only the flush/encode
pipeline can return. `WriteBufferController` is that enforcement point, a
process-level (or per-`TableWrite`) accountant shared by every merge-tree
writer of an ingest job:

  reserve(n)     admission for n incoming bytes. Below the stop trigger
                 (`write.buffer.stop-trigger` x `write.buffer.max-memory`)
                 writes are admitted immediately. Above it the caller is
                 THROTTLED: a bounded block (deadline
                 `write.buffer.block-timeout`) waiting for in-flight flushes
                 to release budget. On deadline the write is REJECTED with a
                 typed `WriterBackpressureError` — load shedding the caller
                 can catch, back off, and replay, instead of an OOM nobody
                 can catch.
  release(n)     budget returned: an offloaded flush finished encoding, or
                 a writer was closed/abandoned (commit-conflict teardown)
                 with bytes still reserved. Releasing is idempotent at the
                 writer layer (MergeTreeWriter tracks its accounted bytes
                 exactly once), so a conflict-replanned bucket can never
                 double-count.
  flush_begin()  pending-flush depth cap: at most
                 `write.buffer.max-pending-flushes` memtables may sit behind
                 the flush workers at once. When the cap is hit the writer
                 encodes INLINE (the caller pays — natural backpressure)
                 rather than queueing unbounded memtables behind a slow
                 encoder.

Backpressure state machine (see ARCHITECTURE.md "Traffic soak & flow
control"): OK -> THROTTLING (in_use >= stop trigger; writers block and
drain their own memtables) -> REJECTING (deadline exceeded; typed error)
-> back to OK as flush workers release. Metrics land in the soak{...}
group: writes_throttled, writes_rejected, backpressure_ms.
"""

from __future__ import annotations

import threading
import time

__all__ = ["WriterBackpressureError", "WriteBufferController"]


class WriterBackpressureError(RuntimeError):
    """Write rejected: the write buffer stayed at/above the stop trigger for
    the full `write.buffer.block-timeout`. The write was NOT buffered — the
    caller may shed it, back off and replay it, or surface the pressure to
    its own upstream. Typed (rather than a bare RuntimeError) so ingest
    frontends can distinguish load shedding from data errors."""


class WriteBufferController:
    """Byte/flush-depth accountant shared by the merge-tree writers of one
    ingest job (or, when passed explicitly, by many concurrent jobs — the
    soak harness shares one across every writer thread to model a global
    host-memory budget)."""

    def __init__(
        self,
        max_memory: int,
        stop_trigger: float = 0.9,
        block_timeout_ms: int = 10_000,
        max_pending_flushes: int = 4,
    ):
        self.max_memory = int(max_memory)
        self.stop_trigger = float(stop_trigger)
        self.block_timeout_ms = int(block_timeout_ms)
        self.max_pending_flushes = int(max_pending_flushes)
        self._soft = int(self.max_memory * self.stop_trigger) if self.max_memory > 0 else 0
        self._cond = threading.Condition()
        self._in_use = 0
        self._pending_flushes = 0
        self._throttled = 0
        self._rejected = 0
        # total millis writers spent blocked in THIS controller's admission
        # (the network servers report it per ingest surface — the global
        # soak{backpressure_ms} histogram mixes every controller together)
        self._backpressure_ms = 0.0
        # REJECTING latch: a deadline reject happened and the buffer has not
        # dropped below the stop trigger since — remote frontends should shed
        # immediately instead of paying the block timeout themselves
        self._rejecting = False

    # ---- construction ---------------------------------------------------
    @classmethod
    def from_options(cls, options) -> "WriteBufferController | None":
        """None when admission control is off (write.buffer.max-memory=0,
        the default — existing write paths are untouched)."""
        from ..options import CoreOptions

        max_memory = options.write_buffer_max_memory
        if max_memory <= 0:
            return None
        return cls(
            max_memory,
            stop_trigger=options.options.get(CoreOptions.WRITE_BUFFER_STOP_TRIGGER),
            block_timeout_ms=options.write_buffer_block_timeout_ms,
            max_pending_flushes=options.options.get(CoreOptions.WRITE_BUFFER_MAX_PENDING_FLUSHES),
        )

    # ---- byte budget ----------------------------------------------------
    def _admissible(self, nbytes: int) -> bool:
        # an empty buffer always admits, even an oversized single batch:
        # rejecting it forever would deadlock the caller against itself
        return self._in_use == 0 or self._in_use + nbytes <= self._soft

    def try_reserve(self, nbytes: int) -> bool:
        """Non-blocking admission. False = over the stop trigger; the caller
        should drain its own memtable (freeing its share) before falling
        back to the blocking reserve()."""
        with self._cond:
            if not self._admissible(nbytes):
                return False
            self._in_use += nbytes
            return True

    def reserve(self, nbytes: int) -> None:
        """Blocking admission: throttle (bounded block) then reject."""
        from ..metrics import soak_metrics

        with self._cond:
            if self._admissible(nbytes):
                self._in_use += nbytes
                return
            g = soak_metrics()
            g.counter("writes_throttled").inc()
            self._throttled += 1
            t0 = time.perf_counter()
            deadline = t0 + self.block_timeout_ms / 1000.0
            try:
                while not self._admissible(nbytes):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        g.counter("writes_rejected").inc()
                        self._rejected += 1
                        self._rejecting = True
                        raise WriterBackpressureError(
                            f"write buffer full: {self._in_use}/{self.max_memory} bytes in "
                            f"use (stop trigger {self._soft}), {self._pending_flushes} "
                            f"flushes pending; blocked {self.block_timeout_ms} ms "
                            f"(write.buffer.block-timeout) without drain"
                        )
                    self._cond.wait(remaining)
                self._in_use += nbytes
            finally:
                blocked_ms = (time.perf_counter() - t0) * 1000
                self._backpressure_ms += blocked_ms
                g.histogram("backpressure_ms").update(blocked_ms)

    def release(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        with self._cond:
            self._in_use = max(0, self._in_use - nbytes)
            if self._in_use < self._soft or self._soft <= 0:
                self._rejecting = False
            self._cond.notify_all()

    # ---- pending-flush depth cap ---------------------------------------
    def flush_begin(self) -> bool:
        """Claim a pending-flush slot. False = cap held for the full block
        timeout; the caller must encode inline instead of queueing."""
        from ..metrics import soak_metrics

        with self._cond:
            if self.max_pending_flushes <= 0 or self._pending_flushes < self.max_pending_flushes:
                self._pending_flushes += 1
                return True
            g = soak_metrics()
            g.counter("writes_throttled").inc()
            self._throttled += 1
            t0 = time.perf_counter()
            deadline = t0 + self.block_timeout_ms / 1000.0
            try:
                while self._pending_flushes >= self.max_pending_flushes:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        return False
                    self._cond.wait(remaining)
                self._pending_flushes += 1
                return True
            finally:
                blocked_ms = (time.perf_counter() - t0) * 1000
                self._backpressure_ms += blocked_ms
                g.histogram("backpressure_ms").update(blocked_ms)

    def flush_end(self) -> None:
        with self._cond:
            self._pending_flushes = max(0, self._pending_flushes - 1)
            self._cond.notify_all()

    # ---- introspection --------------------------------------------------
    @property
    def in_use(self) -> int:
        return self._in_use

    @property
    def pending_flushes(self) -> int:
        return self._pending_flushes

    def health_dict(self) -> dict:
        """Point-in-time flow-control surface, JSON-serializable with a
        STABLE schema: both network servers (KV + Flight), the soak
        supervisors, and TableWrite.health() all report this exact shape, so
        a remote ingest frontend can shed on `state` without caring which
        surface answered. States: ok → throttling (at/over the stop
        trigger — writers block bounded) → rejecting (a block deadline
        expired and pressure has not released — shed immediately).
        retry_after_ms is the server's backoff hint for a BUSY response,
        derived from the admission state."""
        with self._cond:
            state = "ok"
            if self._in_use >= self._soft > 0:
                state = "rejecting" if self._rejecting else "throttling"
            retry_after = 0
            if state == "throttling":
                # half the block budget: pressure is draining, come back soon
                retry_after = max(1, self.block_timeout_ms // 2)
            elif state == "rejecting":
                # a full block budget already failed once — back off hard
                retry_after = self.block_timeout_ms
            return {
                "state": state,
                "buffered_bytes": self._in_use,
                "max_memory": self.max_memory,
                "stop_trigger_bytes": self._soft,
                "pending_flushes": self._pending_flushes,
                "max_pending_flushes": self.max_pending_flushes,
                "writes_throttled": self._throttled,
                "writes_rejected": self._rejected,
                "backpressure_ms": round(self._backpressure_ms, 3),
                "retry_after_ms": retry_after,
            }

    # kept as an alias: PR-8 callers (TableWrite.health, the thread soak)
    # predate the stable-schema rename
    health = health_dict
