"""The merge-tree writer: memtable, flush-through-merge, compaction hooks.

Parity: /root/reference/paimon-core/.../mergetree/MergeTreeWriter.java:57 —
assigns sequence numbers (:164), buffers into a sort buffer, flushes the
buffer through the merge function into rolling level-0 files
(flushWriteBuffer:209-260), triggers compaction, and accumulates the
CommitIncrement returned by prepareCommit (:263-278).

The memtable here is a list of column batches; "sorting the buffer" is the
same device merge kernel used everywhere else — flush = merge(concat(buffer)).
"""

from __future__ import annotations

import numpy as np

from ..data.batch import ColumnBatch
from ..options import CoreOptions
from ..types import RowKind
from .compact import CompactResult, MergeTreeCompactManager
from .datafile import DataFileMeta, KeyValueFileWriterFactory
from .kv import KVBatch
from .manifest import CommitMessage

__all__ = ["MergeTreeWriter"]


class MergeTreeWriter:
    def __init__(
        self,
        partition: tuple,
        bucket: int,
        total_buckets: int,
        writer_factory: KeyValueFileWriterFactory,
        merge_executor,
        compact_manager: MergeTreeCompactManager | None,
        options: CoreOptions,
        restored_max_seq: int = -1,
        admission=None,
        debt_gate=None,
    ):
        self.partition = partition
        self.bucket = bucket
        self.total_buckets = total_buckets
        self.writer_factory = writer_factory
        self.merge = merge_executor
        self.compact_manager = compact_manager
        self.options = options
        self.seq = restored_max_seq + 1
        # admission control (core/admission.py): every buffered byte is
        # reserved against the shared WriteBufferController and released
        # exactly once — when the flush that drains it finishes encoding, or
        # when this writer is closed/abandoned (commit-conflict teardown).
        # _accounted tracks this writer's outstanding reservation so teardown
        # can release the remainder without double-counting what in-flight
        # flush workers already returned.
        self.admission = admission
        # debt-admission gate (ISSUE 12, PR 11 follow-up): a zero-arg
        # resolver returning the table's running AdaptiveCompactorService
        # (or None). Write-only writers have no inline compaction manager,
        # so every flush — the moment a new sorted run is born — first
        # admits against the service's read-amp ceiling and settles the
        # charge once the run's files land. Resolved per flush so a service
        # started after this writer still bounds it.
        self.debt_gate = debt_gate
        self._accounted = 0
        self._slots_held = 0
        import threading

        self._acct_lock = threading.Lock()
        self._buffer: list[KVBatch] = []
        self._buffered_rows = 0
        self._buffered_bytes = 0
        self._buffer_seq_ordered = True
        # read-your-writes visibility: batches drained from the memtable but
        # whose flush has not yet landed level-0 files stay listed here, so
        # delta_snapshot never has a blind window between flush_dispatch
        # clearing the buffer and flush_complete publishing _new_files
        self._inflight_delta: list[KVBatch] = []
        self._new_files: list[DataFileMeta] = []
        self._compact_before: list[DataFileMeta] = []
        self._compact_after: list[DataFileMeta] = []
        self._changelog: list[DataFileMeta] = []
        self._compact_changelog: list[DataFileMeta] = []
        # pipelined flush (parallel/pipeline.py consumer 3): auto-flushes
        # triggered by write() offload the merge-resolve + file encode (+
        # any resulting compaction) to a single background worker, so the
        # next memtable fills while the previous one encodes. One worker +
        # FIFO keeps the levels/compaction state transitions in exactly the
        # sequential order — output is bit-identical. prepare_commit (and the
        # public flush()) is the barrier; worker errors surface there.
        from ..parallel.pipeline import pipeline_config

        self._async_flush = pipeline_config(options)[0] > 0
        self._flush_pool = None
        self._flush_pending: list = []

    # ---- ingest --------------------------------------------------------
    def write(self, data: ColumnBatch, kinds: np.ndarray | None = None) -> None:
        """Append a batch of rows; sequence numbers are assigned in arrival
        order (MergeTreeWriter.write: newSequenceNumber per record)."""
        n = data.num_rows
        if n == 0:
            return
        kv = KVBatch.from_rows(data, self.seq, kinds)
        self._reserve(kv.byte_size())  # may raise: seq/buffer untouched
        self.seq += n
        self._buffer.append(kv)
        self._buffered_rows += n
        self._buffered_bytes += kv.byte_size()
        if self._should_flush():
            self._flush_async()

    def write_kv(self, kv: KVBatch) -> None:
        if kv.num_rows == 0:
            return
        self._reserve(kv.byte_size())  # may raise: buffer untouched
        # externally assigned seqs may interleave: disable the stability
        # shortcut for this memtable generation
        self._buffer_seq_ordered = False
        self._buffer.append(kv)
        self.seq = max(self.seq, int(kv.seq.max()) + 1)
        self._buffered_rows += kv.num_rows
        self._buffered_bytes += kv.byte_size()
        if self._should_flush():
            self._flush_async()

    # ---- admission accounting ------------------------------------------
    def _reserve(self, nbytes: int) -> None:
        """Admission for nbytes of memtable. Over the stop trigger, first
        drain OUR OWN memtable through the (offloaded) flush — freeing the
        share this writer itself holds — then fall back to the bounded
        blocking reserve (which raises WriterBackpressureError on deadline,
        with nothing buffered and self.seq untouched)."""
        if self.admission is None:
            return
        if not self.admission.try_reserve(nbytes):
            if self._buffered_bytes > 0:
                self._flush_async()
            self.admission.reserve(nbytes)
        with self._acct_lock:
            self._accounted += nbytes

    def _acct_release(self, nbytes: int) -> None:
        if self.admission is None or nbytes <= 0:
            return
        with self._acct_lock:
            nbytes = min(nbytes, self._accounted)
            self._accounted -= nbytes
        self.admission.release(nbytes)

    def _acct_release_all(self) -> None:
        if self.admission is None:
            return
        with self._acct_lock:
            n, self._accounted = self._accounted, 0
        self.admission.release(n)

    def _should_flush(self) -> bool:
        """Byte budget first (reference MemorySegmentPool accounts bytes —
        wide rows must not blow host memory before a row cap), row cap as
        the secondary bound."""
        return (
            self._buffered_bytes >= self.options.write_buffer_size
            or self._buffered_rows >= self.options.write_buffer_rows
        )

    # ---- flush ---------------------------------------------------------
    def flush(self) -> None:
        """Synchronous barrier: drain the memtable AND wait for every
        offloaded flush to finish (errors from background encodes re-raise
        here). Same post-conditions as the sequential path."""
        self._flush_async()
        self._drain_flushes()

    def _flush_async(self) -> None:
        """Drain the memtable; run the complete phase on the flush worker
        when pipelining is on (so the caller returns to filling the next
        memtable), inline otherwise. FIFO on one worker = sequential order."""
        from ..parallel.executor import current_mesh_context

        state = self.flush_dispatch()
        if state is None:
            return
        if not self._async_flush or current_mesh_context() is not None:
            self.flush_complete(state)
            return
        if self.admission is not None and not self.admission.flush_begin():
            # pending-flush depth cap held for the full block timeout: a slow
            # encoder must not queue unbounded memtables — encode inline, the
            # caller pays (that IS the backpressure)
            self.flush_complete(state)
            return
        if self.admission is not None:
            with self._acct_lock:
                self._slots_held += 1
        if self._flush_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            from ..parallel.pipeline import FLUSH_THREAD_PREFIX

            self._flush_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=FLUSH_THREAD_PREFIX
            )
        from ..metrics import pipeline_metrics

        import time as _time

        g = pipeline_metrics()
        busy = g.histogram("flush_busy_ms")
        g.counter("splits_prefetched").inc()

        def run():
            t0 = _time.perf_counter()
            try:
                self.flush_complete(state)
            finally:
                if self.admission is not None:
                    with self._acct_lock:
                        self._slots_held -= 1
                    self.admission.flush_end()
                busy.update((_time.perf_counter() - t0) * 1000)

        self._flush_pending.append(self._flush_pool.submit(run))

    def _drain_flushes(self) -> None:
        """Wait for offloaded flushes; the FIRST failure re-raises after the
        rest were cancelled/awaited (a failed flush must not silently let a
        later one keep mutating levels)."""
        pending, self._flush_pending = self._flush_pending, []
        error = None
        for f in pending:
            if error is not None:
                f.cancel()
                continue
            try:
                f.result()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                error = exc
        if error is not None:
            self._shutdown_flush_pool()
            raise error

    def _shutdown_flush_pool(self) -> None:
        if self._flush_pool is not None:
            self._flush_pool.shutdown(wait=True, cancel_futures=True)
            self._flush_pool = None
        if self.admission is not None:
            # with the pool down, any slot still held belongs to a flush
            # that was cancelled before running (its run() never reached
            # flush_end) — return those so the depth cap cannot wedge
            with self._acct_lock:
                slots, self._slots_held = self._slots_held, 0
            for _ in range(slots):
                self.admission.flush_end()

    def close(self) -> None:
        """Release the flush worker without committing. Pending background
        errors are swallowed (close is the abandon path; prepare_commit is
        where failures must surface). Every byte this writer still holds
        reserved — undrained memtable, a cancelled flush's batch, a failed
        dispatch — returns to the admission controller EXACTLY once here, so
        abandoning a bucket after a commit conflict re-admits blocked rivals
        instead of leaking budget."""
        for f in self._flush_pending:
            f.cancel()
        try:
            for f in self._flush_pending:
                if not f.cancelled():
                    f.exception()
        finally:
            self._flush_pending = []
            self._shutdown_flush_pool()  # also returns cancelled flushes' depth slots
            self._acct_release_all()
            self._inflight_delta.clear()

    def flush_dispatch(self):
        """Phase 1 of a (possibly mesh-batched) flush: drain the memtable,
        persist the input changelog, and dispatch the merge. Under an active
        MeshBatchContext the merge job is only enqueued — every bucket's job
        runs in one batched mesh call when the first flush_complete resolves.

        Any offloaded flush_complete still in flight lands first (and its
        error surfaces here): at most one flush is ever pending, so every
        caller — including the mesh path's direct dispatch/complete — sees
        levels/compaction state in strict flush order. The overlap window is
        the memtable fill between two flushes, which is the point."""
        self._drain_flushes()
        if not self._buffer:
            return None
        gate = self.debt_gate() if self.debt_gate is not None else None
        if gate is not None:
            # block (bounded) while this bucket's projected sorted-run count
            # sits at/over the read-amp ceiling, then charge the in-flight
            # run this flush is about to create; flush_complete settles. A
            # timeout proceeds — the breach is the scheduler's to drain, the
            # gate must never wedge ingest on a stalled compactor.
            from ..options import CoreOptions as _CO

            timeout_ms = self.options.options.get(_CO.COMPACTION_ADAPTIVE_INGEST_GATE_TIMEOUT)
            gate.admit([(self.partition, self.bucket)], timeout_s=timeout_ms / 1000.0)
        from ..resilience.faults import crash_point

        # memtable full, nothing drained: a kill here loses only rows no
        # commit ever acknowledged
        crash_point("flush:before-dispatch")
        kv = KVBatch.concat(self._buffer) if len(self._buffer) > 1 else self._buffer[0]
        drained_bytes = self._buffered_bytes
        self._inflight_delta.append(kv)  # visible to delta_snapshot until the L0 files land
        self._buffer.clear()
        self._buffered_rows = 0
        self._buffered_bytes = 0
        from ..options import ChangelogProducer

        producer = self.options.changelog_producer
        if producer == ChangelogProducer.INPUT:
            # the raw input IS the changelog (reference: input producer
            # persists the flushed buffer as changelog files)
            self._changelog.extend(
                self.writer_factory.write(
                    kv, level=0, file_source="append", prefix="changelog", sorted_input=False
                )
            )
        # memtable rows arrive in seq order: stability replaces seq lanes
        buffer_seq_ordered = self._buffer_seq_ordered
        handle = self.merge.merge_async(kv, seq_ascending=buffer_seq_ordered)
        self._buffer_seq_ordered = True
        return (handle, buffer_seq_ordered, drained_bytes, gate, kv)

    def flush_complete(self, state) -> None:
        """Phase 2: resolve the merge and write level-0 files + changelog,
        then trigger compaction. The batch's buffer reservation returns to
        the admission controller when the encode lands (or fails) — that is
        the moment the bytes stop being host-memory the flush pipeline owes.
        The debt-gate charge settles here too: landed when the level-0 run's
        files exist, abandoned when the flush failed."""
        handle, buffer_seq_ordered, drained_bytes, gate, kv = state
        landed = False
        try:
            self._flush_complete_inner(handle, buffer_seq_ordered)
            landed = True
        finally:
            self._acct_release(drained_bytes)
            try:
                # the L0 files (or the failure) are published: the raw batch
                # leaves the read-your-writes in-flight window
                self._inflight_delta.remove(kv)
            except ValueError:
                pass  # close() may have cleared the window already
            if gate is not None:
                gate.settle([(self.partition, self.bucket)], landed=landed)

    def _flush_complete_inner(self, handle, buffer_seq_ordered) -> None:
        merged = self.merge.merge_resolve(handle)
        from ..options import ChangelogProducer

        producer = self.options.changelog_producer
        from ..options import CoreOptions

        lookup_wait = self.options.options.get(CoreOptions.CHANGELOG_PRODUCER_LOOKUP_WAIT)
        if producer == ChangelogProducer.LOOKUP and lookup_wait:
            # exact changelog at WRITE time: look up the previous visible
            # value of each incoming key (reference LookupChangelogMerge-
            # FunctionWrapper / LookupMergeTreeCompactRewriter — here the
            # "lookup" is a vectorized merge-read of the overlapping files
            # diffed against the new state with the same kernel as the
            # full-compaction producer).  changelog-producer.lookup-wait=false
            # defers production to the next compaction (store.py arms the
            # compaction rewriter's changelog emitter for that case) so the
            # commit never waits on the lookup.
            cl = self._lookup_changelog(merged, buffer_seq_ordered)
            if cl.num_rows:
                self._changelog.extend(
                    self.writer_factory.write(
                        cl, level=0, file_source="append", prefix="changelog", sorted_input=False
                    )
                )
        files = self.writer_factory.write(merged, level=0, file_source="append")
        from ..resilience.faults import crash_point

        # level-0 files durable but referenced by no snapshot yet: a kill
        # here strews orphan data files for remove_orphan_files to reclaim
        crash_point("flush:files-written")
        self._new_files.extend(files)
        if self.compact_manager is not None and not self.options.write_only:
            for f in files:
                self.compact_manager.levels.level0.insert(0, f)
            self._maybe_compact()

    def _lookup_changelog(self, merged: KVBatch, buffer_seq_ordered: bool = True) -> KVBatch:
        """Diff the bucket's visible state before vs after this flush,
        restricted to the flushed key range."""
        from ..data.keys import encode_key_lanes, exact_string_pool
        from ..types import TypeRoot
        from .changelog import full_compaction_changelog
        from .read import MergeFileSplitRead

        if merged.num_rows == 0 or self.compact_manager is None:
            return merged.slice(0, 0)
        key_names = self.merge.key_names
        lo = tuple(merged.data.column(k).values[0] for k in key_names)
        hi = tuple(merged.data.column(k).values[-1] for k in key_names)
        overlapping = [
            f
            for f in self.compact_manager.levels.all_files()
            if not (f.max_key < lo or f.min_key > hi)
        ]
        reader = MergeFileSplitRead(
            self.compact_manager.rewriter.reader_factory, self.merge, key_names
        )
        before = reader.read_kv(
            overlapping, drop_delete=True, deletion_vectors=self.compact_manager.rewriter.deletion_vectors
        )
        # after = before + new batch merged; stability only applies when the
        # buffer's seqs were monotone (write_kv may interleave external seqs)
        after = self.merge.merge(
            KVBatch.concat([before, merged]), seq_ascending=buffer_seq_ordered
        ).drop_deletes()
        pools = {}
        for k in key_names:
            root = merged.data.schema.field(k).type.root
            if root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY):
                pools[k] = exact_string_pool([before.data.column(k), after.data.column(k)])
        lanes_before = encode_key_lanes(before.data, key_names, pools)
        lanes_after = encode_key_lanes(after.data, key_names, pools)
        return full_compaction_changelog(
            before,
            after,
            lanes_before,
            lanes_after,
            row_deduplicate=self.options.options.get(CoreOptions.CHANGELOG_PRODUCER_ROW_DEDUPLICATE),
        )

    def _maybe_compact(self, full: bool = False) -> None:
        assert self.compact_manager is not None
        result = self.compact_manager.trigger_compaction(full=full)
        self._absorb(result)

    def compact(self, full: bool = False) -> None:
        """Explicit compaction (dedicated compact jobs / full-compaction)."""
        self.flush()
        if self.compact_manager is not None:
            self._maybe_compact(full=full)

    def compact_dispatch(self, full: bool = False):
        """Phase 1 of an explicit compaction (caller must have flushed)."""
        self._drain_flushes()  # levels must be settled before planning
        if self.compact_manager is None:
            return None
        return self.compact_manager.compact_dispatch(full)

    def compact_complete(self, state) -> None:
        if state is None or self.compact_manager is None:
            return
        self._absorb(self.compact_manager.compact_complete(state))

    def _absorb(self, result: CompactResult | None) -> None:
        if result is None or result.is_empty():
            return
        # cancel out files that this very commit created and then compacted
        new_names = {f.file_name for f in self._new_files}
        created_then_compacted = [f for f in result.before if f.file_name in new_names]
        self._compact_before.extend(f for f in result.before if f.file_name not in new_names)
        # files created and consumed within one commit still need ADD+DELETE
        # to keep the manifest chain consistent — reference keeps both too
        self._compact_before.extend(created_then_compacted)
        self._compact_after.extend(result.after)
        self._compact_changelog.extend(result.changelog)

    # ---- commit --------------------------------------------------------
    def prepare_commit(self) -> CommitMessage:
        try:
            self.flush()  # barrier: offloaded encodes land before the message builds
        finally:
            # torn down on the ERROR path too: a flush-worker failure
            # re-raised here must not leak the 1-worker paimon-flush
            # executor (the happy path shut it down; a dispatch-phase
            # failure — e.g. the input-changelog write — left it alive)
            self._shutdown_flush_pool()
        # a file produced by one compaction round and consumed by a later
        # round within the same commit cancels out of the message. Keyed by
        # (name, LEVEL), not name alone: an upgrade emits DELETE(F@k) +
        # ADD(F@higher) under ONE name — name-based cancel would erase the
        # whole chain, deleting the rewrite's inputs while never adding F
        # (silent row loss once the orphan sweep reclaims it). With the
        # level in the key only the true create-then-consume pair (F@k in
        # both lists) cancels, leaving DELETE inputs + ADD F@higher.
        before_keys = {(f.file_name, f.level) for f in self._compact_before}
        after_keys = {(f.file_name, f.level) for f in self._compact_after}
        cancel = before_keys & after_keys
        msg = CommitMessage(
            partition=self.partition,
            bucket=self.bucket,
            total_buckets=self.total_buckets,
            new_files=list(self._new_files),
            compact_before=[f for f in self._compact_before if (f.file_name, f.level) not in cancel],
            compact_after=[f for f in self._compact_after if (f.file_name, f.level) not in cancel],
            changelog_files=list(self._changelog),
            compact_changelog_files=list(self._compact_changelog),
        )
        self._new_files.clear()
        self._compact_before.clear()
        self._compact_after.clear()
        self._changelog.clear()
        self._compact_changelog.clear()
        return msg

    def delta_snapshot(self) -> tuple[list[KVBatch], list[DataFileMeta]]:
        """Point-in-time view of this writer's uncommitted state for the
        read-your-writes get tier: buffered memtable batches (plus any
        drained-but-not-yet-landed flush input) and the level-0 files no
        snapshot references yet. List copies — safe to take from a serving
        thread while this writer keeps ingesting (a row caught by BOTH an
        in-flight batch and its landed file resolves identically: same key,
        same sequence, same value)."""
        return list(self._buffer) + list(self._inflight_delta), list(self._new_files)

    @property
    def max_sequence_number(self) -> int:
        return self.seq - 1

    def health(self) -> dict:
        """Point-in-time writer state for TableWrite.health()."""
        return {
            "buffered_bytes": self._buffered_bytes,
            "buffered_rows": self._buffered_rows,
            "pending_flushes": len(self._flush_pending),
            "reserved_bytes": self._accounted,
        }
