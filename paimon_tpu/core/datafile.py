"""Data file metadata + the keyed read/write plumbing.

Parity: /root/reference/paimon-core/.../io/ —
  DataFileMeta.java:54-109 (fileName, size, rowCount, minKey/maxKey,
  keyStats/valueStats, seq range, schemaId, level, deleteRowCount, fileSource),
  KeyValueDataFileWriter (stats collection), RollingFileWriter (target-size
  rolling), KeyValueFileReaderFactory.java:63 (format reader + schema
  evolution mapping + projection/predicate pushdown).
"""

from __future__ import annotations

import base64
from dataclasses import dataclass, field, replace
from typing import Iterator, Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch
from ..data.casting import cast_column
from ..data.predicate import FieldStats, Predicate
from ..format import collect_stats, get_format, stats_from_json, stats_to_json
from ..fs import FileIO
from ..types import DataField, RowKind, RowType
from ..utils import new_file_name, now_millis
from .kv import SEQUENCE_FIELD_NAME, VALUE_KIND_FIELD_NAME, KVBatch, kv_disk_schema

__all__ = ["DataFileMeta", "KeyValueFileWriterFactory", "KeyValueFileReaderFactory"]


@dataclass(frozen=True)
class DataFileMeta:
    file_name: str
    file_size: int
    row_count: int
    min_key: tuple  # first key tuple (file rows are key-sorted)
    max_key: tuple
    key_stats: dict[str, FieldStats]
    value_stats: dict[str, FieldStats]
    min_sequence_number: int
    max_sequence_number: int
    schema_id: int
    level: int
    delete_row_count: int = 0
    creation_time_millis: int = 0
    file_source: str = "append"  # append | compact
    extra_files: tuple[str, ...] = ()
    embedded_index: bytes | None = None  # small PTIX payload carried in the manifest

    def upgrade(self, level: int) -> "DataFileMeta":
        return replace(self, level=level)

    def to_dict(self) -> dict:
        return {
            "fileName": self.file_name,
            "fileSize": self.file_size,
            "rowCount": self.row_count,
            "minKey": list(self.min_key),
            "maxKey": list(self.max_key),
            "keyStats": stats_to_json(self.key_stats),
            "valueStats": stats_to_json(self.value_stats),
            "minSequenceNumber": self.min_sequence_number,
            "maxSequenceNumber": self.max_sequence_number,
            "schemaId": self.schema_id,
            "level": self.level,
            "deleteRowCount": self.delete_row_count,
            "creationTimeMillis": self.creation_time_millis,
            "fileSource": self.file_source,
            "extraFiles": list(self.extra_files),
            # base64 so the meta stays JSON-serializable (reference
            # DataFileMeta.embeddedIndex, file-index.in-manifest-threshold)
            "embeddedIndex": (
                None
                if self.embedded_index is None
                else base64.b64encode(self.embedded_index).decode()
            ),
        }

    @staticmethod
    def from_dict(d: dict) -> "DataFileMeta":
        return DataFileMeta(
            d["fileName"],
            d["fileSize"],
            d["rowCount"],
            tuple(d["minKey"]),
            tuple(d["maxKey"]),
            stats_from_json(d["keyStats"]),
            stats_from_json(d["valueStats"]),
            d["minSequenceNumber"],
            d["maxSequenceNumber"],
            d["schemaId"],
            d["level"],
            d.get("deleteRowCount", 0),
            d.get("creationTimeMillis", 0),
            d.get("fileSource", "append"),
            tuple(d.get("extraFiles", ())),
            (
                None
                if d.get("embeddedIndex") is None
                else base64.b64decode(d["embeddedIndex"])
            ),
        )


def _key_tuple(batch: ColumnBatch, key_names: Sequence[str], row: int) -> tuple:
    # value_at: two boundary rows must not expand a code-backed column
    return tuple(batch.column(k).value_at(row) for k in key_names)


def _to_py_tuple(t: tuple) -> tuple:
    return tuple(x.item() if hasattr(x, "item") else x for x in t)


class KeyValueFileWriterFactory:
    """Writes key-sorted KVBatches as data files with stats + optional bloom
    index sidecars."""

    def __init__(
        self,
        file_io: FileIO,
        bucket_dir: str,
        value_schema: RowType,
        key_names: Sequence[str],
        schema_id: int,
        file_format: str = "parquet",
        compression: str = "zstd",
        target_file_size: int = 128 << 20,
        bloom_columns: Sequence[str] = (),
        bloom_fpp: float = 0.05,
        key_bloom: bool = False,
        key_bloom_fpp: float = 0.001,
        index_in_manifest_threshold: int = 500,
        keyed: bool = True,
        format_options: dict | None = None,
        include_key_columns: bool = False,
        per_level_format: dict[int, str] | None = None,
        per_level_compression: dict[int, str] | None = None,
    ):
        self.file_io = file_io
        self.bucket_dir = bucket_dir
        self.value_schema = value_schema
        self.key_names = list(key_names)
        self.schema_id = schema_id
        self.format_id = file_format
        self.compression = compression
        self.target_file_size = target_file_size
        self.bloom_columns = list(bloom_columns)
        self.bloom_fpp = bloom_fpp
        # composite primary-key bloom (file-index.bloom-filter.primary-key.
        # enabled): written at flush AND compaction time — both routes land
        # here — so the batched get path can prune any file without data IO
        self.key_bloom = bool(key_bloom) and keyed and bool(key_names)
        self.key_bloom_fpp = key_bloom_fpp
        self.index_in_manifest_threshold = index_in_manifest_threshold
        # keyed=False: append-only tables — plain rows on disk, no
        # _SEQUENCE_NUMBER/_VALUE_KIND columns, no key range
        # (reference AppendOnlyFileStore / AppendOnlyWriter)
        self.keyed = keyed
        self.format_options = format_options or {}
        # reference-layout data files: duplicate trimmed PK as _KEY_ columns
        self.include_key_columns = include_key_columns
        # per-LSM-level overrides (reference file.format.per.level /
        # file.compression.per.level); readers pick the format off the file
        # extension, so levels can mix freely
        self.per_level_format = per_level_format or {}
        self.per_level_compression = per_level_compression or {}

    def _estimate_row_bytes(self, batch: ColumnBatch) -> int:
        total = 0
        for f in batch.schema.fields:
            dt = f.type.numpy_dtype()
            if dt == np.dtype(object):
                total += 16  # rough var-len average pre-compression
            else:
                total += dt.itemsize
        return max(total, 1)

    def write(
        self, kv: KVBatch, level: int, file_source: str = "append", prefix: str = "data",
        sorted_input: bool = True, measured_row_bytes: float | None = None,
    ) -> list[DataFileMeta]:
        """Rolls into multiple files at target size. Input must be key-sorted
        unless sorted_input=False (changelog files preserve event order; key
        min/max are then computed instead of taken from the edges).
        measured_row_bytes overrides the schema-based width estimate (callers
        with skewed var-length data pass actual bytes — the reference's
        sort-compaction.range-strategy=size)."""
        n = kv.num_rows
        if n == 0:
            return []
        row_bytes = measured_row_bytes or self._estimate_row_bytes(kv.data)
        rows_per_file = max(1, int(self.target_file_size / max(row_bytes, 1)))
        out: list[DataFileMeta] = []
        for start in range(0, n, rows_per_file):
            out.append(
                self._write_one(
                    kv.slice(start, min(start + rows_per_file, n)), level, file_source, prefix, sorted_input
                )
            )
        return out

    def _key_min_max(self, batch: ColumnBatch, sorted_input: bool) -> tuple[tuple, tuple]:
        if not self.key_names:
            return (), ()
        if sorted_input:
            return (
                _to_py_tuple(_key_tuple(batch, self.key_names, 0)),
                _to_py_tuple(_key_tuple(batch, self.key_names, batch.num_rows - 1)),
            )
        from ..ops.dicts import cache_usable

        def sort_key(k):
            col = batch.column(k)
            # codes are rank-order-preserving surrogates: the lexsort
            # permutation's first/last rows match the expanded sort exactly
            return col.dict_cache[1] if cache_usable(col) and col.validity is None else col.values

        order = np.lexsort([sort_key(k) for k in reversed(self.key_names)])
        return (
            _to_py_tuple(_key_tuple(batch, self.key_names, int(order[0]))),
            _to_py_tuple(_key_tuple(batch, self.key_names, int(order[-1]))),
        )

    def _write_one(
        self, kv: KVBatch, level: int, file_source: str, prefix: str = "data", sorted_input: bool = True
    ) -> DataFileMeta:
        format_id = self.per_level_format.get(level, self.format_id)
        compression = self.per_level_compression.get(level, self.compression)
        fmt = get_format(format_id)
        name = new_file_name(prefix, format_id)
        path = f"{self.bucket_dir}/{name}"
        key_cols = self.key_names if (self.keyed and self.include_key_columns) else None
        disk = kv.to_disk_batch(key_cols) if self.keyed else kv.data
        fmt.write(self.file_io, path, disk, compression, format_options=self.format_options)
        extra: list[str] = []
        embedded: bytes | None = None
        if self.bloom_columns or self.key_bloom:
            from ..format.fileindex import build_index_payload, index_path

            hashes = None
            if self.key_bloom:
                from ..table.bucket import key_hashes

                hashes = key_hashes(kv.data, self.key_names)
            payload = build_index_payload(
                kv.data, self.bloom_columns, self.bloom_fpp,
                key_hashes=hashes, key_fpp=self.key_bloom_fpp,
            )
            if payload is not None:
                if len(payload) <= self.index_in_manifest_threshold:
                    # small index rides in the manifest entry: zero extra
                    # opens per file per scan (reference in-manifest-threshold)
                    embedded = payload
                else:
                    self.file_io.write_bytes(index_path(path), payload, overwrite=True)
                    extra.append(name + ".index")
        value_stats = collect_stats(kv.data)
        key_stats = {k: value_stats[k] for k in self.key_names}
        delete_rows = int(np.isin(kv.kind, (int(RowKind.DELETE),)).sum())
        return DataFileMeta(
            file_name=name,
            file_size=self.file_io.get_status(path).size,
            row_count=kv.num_rows,
            min_key=self._key_min_max(kv.data, sorted_input)[0] if self.keyed else (),
            max_key=self._key_min_max(kv.data, sorted_input)[1] if self.keyed else (),
            key_stats=key_stats,
            value_stats=value_stats,
            min_sequence_number=int(kv.seq.min()),
            max_sequence_number=int(kv.seq.max()),
            schema_id=self.schema_id,
            level=level,
            delete_row_count=delete_rows,
            creation_time_millis=now_millis(),
            file_source=file_source,
            extra_files=tuple(extra),
            embedded_index=embedded,
        )


class KeyValueFileReaderFactory:
    """Reads data files back into KVBatches, applying field-id based schema
    evolution (reference SchemaEvolutionUtil.createIndexMapping:78): each
    field of the read schema is located in the file's write schema by id —
    missing => null column, type change => vectorized cast."""

    def __init__(
        self,
        file_io: FileIO,
        bucket_dir: str,
        read_schema: RowType,
        schemas_by_id: dict[int, RowType],
        file_format: str = "parquet",
        keyed: bool = True,
        cache=None,
        format_options: dict | None = None,
    ):
        self.file_io = file_io
        self.bucket_dir = bucket_dir
        self.read_schema = read_schema
        self.schemas_by_id = schemas_by_id
        self.format_id = file_format
        self.keyed = keyed
        # utils.cache data-file cache: data files are immutable, so fully
        # decoded (schema-evolved, cast) KVBatches are cached keyed by
        # (file, projection, system-columns mode, read-field signature,
        # decoder identity). Only predicate-FREE reads participate —
        # predicate pushdown skips row groups/pages, changing the row set
        # per predicate. Cached batches are shared: callers must never
        # mutate column arrays in place (the read path is copy-on-filter
        # throughout).
        self.cache = cache
        # reader-side format options (format.parquet.decoder etc.), applied
        # to the format instance via FileFormat.configure per read
        self.format_options = dict(format_options or {})
        # the dict-domain flag joins the decoder identity: a code-backed
        # batch must never alias an expanded one in the data-file cache
        # (switching merge.dict-domain or its env override stays sound)
        from ..ops.dicts import resolve_dict_domain

        decoder = str(self.format_options.get("format.parquet.decoder") or "arrow")
        if resolve_dict_domain(self.format_options.get("merge.dict-domain")):
            decoder += "+dict"
        self.decoder_id = decoder

    def read(
        self,
        meta: DataFileMeta,
        predicate: Predicate | None = None,
        fields: Sequence[str] | None = None,
        system_columns: bool | str = True,
    ) -> KVBatch:
        """fields: optional subset of read-schema fields to materialize (the
        returned KVBatch's data schema is projected accordingly). Row-group
        skipping depends only on `predicate`, so two reads of the same file
        with the same predicate but different `fields` are row-aligned —
        the pipelined merge path relies on that.

        system_columns: True reads _SEQUENCE_NUMBER + _VALUE_KIND; "kind"
        reads only _VALUE_KIND (seq zeros) — the keys-only merge pipeline
        uses it when run stability replaces sequence comparison, skipping
        the most expensive system column (random int64, ~uncompressible);
        False decodes neither (caller holds them from the key pass)."""
        if not self.keyed:
            system_columns = False
        if predicate is None and self.cache is not None and self.cache.enabled:
            read_names = self.read_schema.field_names if fields is None else list(fields)
            # the read-field signature pins projection AND schema evolution:
            # the same file re-read after an ALTER maps/casts differently
            sig = tuple((f.id, f.name, repr(f.type)) for f in (self.read_schema.field(n) for n in read_names))
            # decoder identity is part of the key: a batch decoded by the
            # arrow backend must never alias one the native backend would
            # produce (switching format.parquet.decoder stays sound).
            # Content-addressed, NOT path-addressed: file names are
            # uuid-unique, so the same file read through another factory —
            # a branch view, a rescale rewrite over a table copy — is a
            # cache hit instead of a cold re-decode.
            key = ("data", meta.file_name, system_columns, sig, fields is None, self.decoder_id)
            return self.cache.get_or_load(
                key,
                lambda: self._decode(meta, None, fields, system_columns),
                lambda kv: kv.byte_size(),
                file_id=meta.file_name,
            )
        return self._decode(meta, predicate, fields, system_columns)

    def _decode(
        self,
        meta: DataFileMeta,
        predicate: Predicate | None,
        fields: Sequence[str] | None,
        system_columns: bool | str,
    ) -> KVBatch:
        data_schema = self.schemas_by_id[meta.schema_id]
        disk_schema = kv_disk_schema(data_schema) if self.keyed else data_schema
        read_fields = (
            self.read_schema.fields
            if fields is None
            else tuple(self.read_schema.field(n) for n in fields)
        )
        # project to the file columns that exist for the read schema
        by_id = {f.id: f for f in data_schema.fields}
        if system_columns is True:
            wanted_cols = [SEQUENCE_FIELD_NAME, VALUE_KIND_FIELD_NAME]
        elif system_columns == "kind":
            wanted_cols = [VALUE_KIND_FIELD_NAME]
        else:
            wanted_cols = []
        mapping: list[tuple[DataField, DataField | None]] = []
        for f in read_fields:
            src = by_id.get(f.id)
            mapping.append((f, src))
            if src is not None:
                wanted_cols.append(src.name)
        # the extension is authoritative: per-level format overrides mean a
        # table legitimately mixes formats across files
        ext = meta.file_name.rsplit(".", 1)[-1]
        fmt = get_format(ext if "." in meta.file_name else self.format_id).configure(self.format_options)
        path = f"{self.bucket_dir}/{meta.file_name}"
        parts = list(fmt.read(self.file_io, path, disk_schema, projection=wanted_cols, predicate=predicate))
        if parts:
            from ..data.batch import concat_batches

            disk = concat_batches(parts)
        else:
            disk = ColumnBatch.empty(disk_schema.project(wanted_cols))
        n = disk.num_rows
        cols: dict[str, Column] = {}
        for f, src in mapping:
            if src is None:
                cols[f.name] = Column(
                    np.zeros(n, dtype=f.type.numpy_dtype()) if f.type.numpy_dtype() != np.dtype(object) else np.full(n, None, dtype=object),
                    np.zeros(n, dtype=np.bool_),
                )
            else:
                col = disk.column(src.name)
                cols[f.name] = cast_column(col, src.type, f.type) if src.type != f.type else col
        out_schema = self.read_schema if fields is None else RowType(read_fields)
        data = ColumnBatch(out_schema, cols)
        if system_columns is True:
            seq = disk.column(SEQUENCE_FIELD_NAME).values.astype(np.int64, copy=False)
            kind = disk.column(VALUE_KIND_FIELD_NAME).values.astype(np.uint8)
        elif system_columns == "kind":
            seq = np.zeros(n, dtype=np.int64)
            kind = disk.column(VALUE_KIND_FIELD_NAME).values.astype(np.uint8)
        else:  # caller already holds seq/kind from the key pass
            seq = np.zeros(n, dtype=np.int64)
            kind = np.zeros(n, dtype=np.uint8)
        return KVBatch(data, seq, kind)
