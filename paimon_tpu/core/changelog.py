"""Changelog production: turning table changes into -U/+U/+I/-D streams.

Parity: /root/reference/paimon-core/.../mergetree/compact/ —
ChangelogMergeTreeRewriter.java:47 / FullChangelogMergeTreeCompactRewriter:43
(full-compaction producer: diff the new top level against the previous one),
and CoreOptions.ChangelogProducer:2107 (none | input | full-compaction |
lookup). The INPUT producer simply persists the raw input of each flush as
changelog files; FULL_COMPACTION computes the exact per-key diff — here as a
vectorized merge of two key-sorted sides (device sort plan + host masks), not
a per-key loop.
"""

from __future__ import annotations

import numpy as np

from ..types import RowKind
from .kv import KVBatch

__all__ = ["full_compaction_changelog"]


def full_compaction_changelog(
    before: KVBatch,
    after: KVBatch,
    key_lanes_before: np.ndarray,
    key_lanes_after: np.ndarray,
    row_deduplicate: bool = True,
) -> KVBatch:
    """Diff two key-sorted, unique-key sides (previous top level vs newly
    compacted result): emits +I for new keys, -U/+U pairs for changed rows,
    -D for vanished keys. Both sides' key lanes must be encoded against the
    same string pools.

    Vectorized: one searchsorted of each side into the other (lane matrices
    compared lexicographically via structured views)."""
    vb = _lane_view(key_lanes_before)
    va = _lane_view(key_lanes_after)
    # membership of after-keys in before (both sorted ascending)
    idx_in_before = np.searchsorted(vb, va)
    has_prev = np.zeros(len(va), dtype=np.bool_)
    safe = np.minimum(idx_in_before, max(len(vb) - 1, 0))
    if len(vb):
        has_prev = vb[safe] == va
    idx_in_after = np.searchsorted(va, vb)
    still_there = np.zeros(len(vb), dtype=np.bool_)
    safe_a = np.minimum(idx_in_after, max(len(va) - 1, 0))
    if len(va):
        still_there = va[safe_a] == vb
    parts: list[KVBatch] = []
    # -D: keys that vanished
    gone = ~still_there
    if gone.any():
        d = before.filter(gone)
        parts.append(KVBatch(d.data, d.seq, np.full(d.num_rows, int(RowKind.DELETE), dtype=np.uint8)))
    # changed rows: -U (old) then +U (new); with row_deduplicate (default
    # here — the diff is vectorized and effectively free) unchanged rows are
    # skipped, else every matched key emits a pair (reference
    # changelog-producer.row-deduplicate, whose default is false)
    if has_prev.any():
        old_rows = before.take(safe[has_prev])
        new_rows = after.filter(has_prev)
        changed = _rows_differ(old_rows, new_rows) if row_deduplicate else np.ones(old_rows.num_rows, dtype=np.bool_)
        if changed.any():
            ub = old_rows.filter(changed)
            ua = new_rows.filter(changed)
            parts.append(KVBatch(ub.data, ub.seq, np.full(ub.num_rows, int(RowKind.UPDATE_BEFORE), dtype=np.uint8)))
            parts.append(KVBatch(ua.data, ua.seq, np.full(ua.num_rows, int(RowKind.UPDATE_AFTER), dtype=np.uint8)))
    # +I: brand-new keys
    fresh = ~has_prev
    if fresh.any():
        i = after.filter(fresh)
        parts.append(KVBatch(i.data, i.seq, np.full(i.num_rows, int(RowKind.INSERT), dtype=np.uint8)))
    if not parts:
        return after.slice(0, 0)
    return KVBatch.concat(parts)


def _lane_view(lanes: np.ndarray) -> np.ndarray:
    """(n, K) uint32 -> (n,) void view comparable lexicographically (C-order
    bytes of big-endian lanes)."""
    if lanes.shape[1] == 0:
        return np.zeros(len(lanes), dtype="V4")
    be = np.ascontiguousarray(lanes.astype(">u4"))
    return be.view(f"V{be.shape[1] * 4}").ravel()


def _rows_differ(a: KVBatch, b: KVBatch) -> np.ndarray:
    """A row changed iff some field's validity flipped or both-valid values
    differ. Value bytes at INVALID slots are unspecified (merge gathers
    leave whatever the source row held), so they must not vote — masking
    them also keeps the verdict identical between the expanded and the
    code-backed (dictionary-domain) column representations."""
    from ..ops.dicts import cache_usable, remap_codes, unify_pools

    out = np.zeros(a.num_rows, dtype=np.bool_)
    for name in a.data.schema.field_names:
        ca, cb = a.data.column(name), b.data.column(name)
        ok_a, ok_b = ca.valid_mask(), cb.valid_mask()
        both = ok_a & ok_b
        if cache_usable(ca) and cache_usable(cb) and (ca.is_code_backed or cb.is_code_backed):
            # compressed-domain diff: unify the two pools once, compare the
            # re-mapped uint32 codes — no string objects, same verdict
            unified, (ra, rb) = unify_pools([ca.dict_cache[0], cb.dict_cache[0]])
            neq = remap_codes(ra, ca.dict_cache[1]) != remap_codes(rb, cb.dict_cache[1])
        else:
            va, ba = ca.values, cb.values
            if va.dtype == np.dtype(object):
                neq = np.fromiter((x != y for x, y in zip(va, ba)), dtype=np.bool_, count=len(va))
            else:
                neq = va != ba
        out |= (neq & both) | (ok_a != ok_b)
    return out
