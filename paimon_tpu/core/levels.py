"""LSM level structure and section partitioning.

Parity: /root/reference/paimon-core/.../mergetree/ —
  SortedRun.java (non-overlapping file sequence), Levels.java:38 (level-0 =
  seq-ordered set of files, levels 1..N one SortedRun each,
  numberOfSortedRuns:115), compact/IntervalPartition.java:33 (partition one
  bucket's files into key-range-disjoint *sections* of minimal SortedRuns —
  greedy min-heap by last maxKey :93-125).

Sections are the unit of merge work: different sections never share a key, so
they concat; within a section every run must sort-merge. On TPU a section is
one kernel launch (or several key-range tiles of one).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field

from .datafile import DataFileMeta

__all__ = ["SortedRun", "Levels", "IntervalPartition"]


@dataclass
class SortedRun:
    """Files sorted by min_key with pairwise-disjoint key ranges."""

    files: list[DataFileMeta] = field(default_factory=list)

    @staticmethod
    def from_sorted(files: list[DataFileMeta]) -> "SortedRun":
        return SortedRun(sorted(files, key=lambda f: f.min_key))

    def total_size(self) -> int:
        return sum(f.file_size for f in self.files)

    def row_count(self) -> int:
        return sum(f.row_count for f in self.files)

    def validate(self) -> None:
        for a, b in zip(self.files, self.files[1:]):
            assert a.max_key < b.min_key, f"overlapping run: {a.file_name} .. {b.file_name}"


class Levels:
    """The level structure of one bucket."""

    def __init__(self, files: list[DataFileMeta], num_levels: int):
        self.num_levels = max(num_levels, max((f.level for f in files), default=0) + 1)
        self.level0: list[DataFileMeta] = sorted(
            [f for f in files if f.level == 0], key=lambda f: -f.max_sequence_number
        )
        self.runs: dict[int, SortedRun] = {}
        for lv in range(1, self.num_levels):
            lv_files = [f for f in files if f.level == lv]
            if lv_files:
                run = SortedRun.from_sorted(lv_files)
                run.validate()
                self.runs[lv] = run

    def all_files(self) -> list[DataFileMeta]:
        out = list(self.level0)
        for lv in sorted(self.runs):
            out.extend(self.runs[lv].files)
        return out

    def number_of_sorted_runs(self) -> int:
        return len(self.level0) + len(self.runs)

    def max_level(self) -> int:
        return self.num_levels - 1

    def non_empty_highest_level(self) -> int:
        for lv in range(self.num_levels - 1, 0, -1):
            if lv in self.runs:
                return lv
        return 0 if self.level0 else -1

    def level_sorted_runs(self) -> list[tuple[int, SortedRun]]:
        """(level, run) pairs; each level-0 file is its own run (reference
        Levels.levelSortedRuns)."""
        out: list[tuple[int, SortedRun]] = [(0, SortedRun([f])) for f in self.level0]
        for lv in sorted(self.runs):
            out.append((lv, self.runs[lv]))
        return out

    def update(self, before: list[DataFileMeta], after: list[DataFileMeta]) -> None:
        remove = {f.file_name for f in before}
        files = [f for f in self.all_files() if f.file_name not in remove] + list(after)
        fresh = Levels(files, self.num_levels)
        self.level0, self.runs, self.num_levels = fresh.level0, fresh.runs, fresh.num_levels


class IntervalPartition:
    """Partition a set of files into sections of minimal sorted runs."""

    def __init__(self, files: list[DataFileMeta]):
        # order by (min_key, max_key) — reference IntervalPartition ctor
        self.files = sorted(files, key=lambda f: (f.min_key, f.max_key))

    def partition(self) -> list[list[SortedRun]]:
        sections: list[list[DataFileMeta]] = []
        current: list[DataFileMeta] = []
        bound = None
        for f in self.files:
            if current and f.min_key > bound:
                sections.append(current)
                current = []
                bound = None
            current.append(f)
            bound = f.max_key if bound is None else max(bound, f.max_key)
        if current:
            sections.append(current)
        return [self._pack(sec) for sec in sections]

    @staticmethod
    def _pack(section: list[DataFileMeta]) -> list[SortedRun]:
        """Greedy minimal-run packing: a min-heap keyed by each run's current
        max_key; a file extends the run it doesn't overlap, else opens a new
        run (reference IntervalPartition.partition :93-125)."""
        heap: list[tuple[tuple, int, list[DataFileMeta]]] = []
        counter = 0
        for f in section:  # already sorted by (min_key, max_key)
            if heap and heap[0][0] < f.min_key:
                _, _, run = heapq.heappop(heap)
                run.append(f)
                heapq.heappush(heap, (f.max_key, counter, run))
            else:
                heapq.heappush(heap, (f.max_key, counter, [f]))
            counter += 1
        return [SortedRun(run) for _, _, run in sorted(heap, key=lambda t: t[1])]
