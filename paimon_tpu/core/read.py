"""Merge-on-read execution: sections -> device merge -> filtered batches.

Parity: /root/reference/paimon-core/.../operation/MergeFileSplitRead.java
(createMergeReader:246-284; the predicate split rule :184-221 — only key
filters may skip files/row-groups of overlapping sections, value filters must
run after merging so a new version can still shadow an old one) and
RawFileSplitRead.java:69 (no-merge path for single-run sections).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..data.batch import ColumnBatch, concat_batches
from ..data.predicate import Predicate, PredicateBuilder, and_
from .datafile import DataFileMeta, KeyValueFileReaderFactory
from .kv import KVBatch
from .levels import IntervalPartition
from .mergefn import MergeExecutor

__all__ = ["MergeFileSplitRead", "order_runs_for_merge"]


_arrow_decode_warm = False


def _ensure_arrow_decode_initialized():
    """One tiny in-memory parquet roundtrip on the CALLING thread before any
    threaded decode: pyarrow's lazily-initialized process globals (thread
    pools, codecs, kernel registries) segfault — reproducibly on this
    single-core rig — when their first-ever initialization races across two
    pool threads both entering read_row_groups. ~1ms, once per process."""
    global _arrow_decode_warm
    if _arrow_decode_warm:
        return
    import io as _io

    import pyarrow as pa
    import pyarrow.parquet as pq

    buf = _io.BytesIO()
    pq.write_table(pa.table({"x": [0]}), buf)
    buf.seek(0)
    pq.ParquetFile(buf).read()
    _arrow_decode_warm = True


def _parallel_map(fn, items, parallelism: int | None = None):
    """Decode several files concurrently (pyarrow/zstd release the GIL, so
    threads give real parallelism on the host-side columnar decode — the
    stage that dominates once the device downloads are compact). Runs on the
    process-wide shared pool (utils.shared_executor): a pool per call paid
    thread spawn/teardown on every split, measurable on small files. Order
    is preserved; single-item lists skip the pool. `parallelism` bounds the
    in-flight window (the scan.parallelism option; None = pool width,
    1 = strictly serial)."""
    items = list(items)
    if len(items) <= 1 or (parallelism is not None and parallelism <= 1):
        return [fn(x) for x in items]
    _ensure_arrow_decode_initialized()
    from ..parallel.pipeline import bounded_map

    return bounded_map(fn, items, parallelism)


def order_runs_for_merge(section) -> tuple[list, bool]:
    """Order a section's runs by ascending sequence range and report whether
    the ranges are pairwise disjoint. Disjoint + ordered means equal keys
    appear in ascending seq order after concatenation, so the merge kernel
    can rely on sort stability instead of uploading sequence lanes."""
    runs = sorted(section, key=lambda r: min(f.min_sequence_number for f in r.files))
    disjoint = True
    prev_max = None
    for r in runs:
        lo = min(f.min_sequence_number for f in r.files)
        hi = max(f.max_sequence_number for f in r.files)
        if prev_max is not None and lo <= prev_max:
            disjoint = False
            break
        prev_max = hi
    return runs, disjoint


class MergeFileSplitRead:
    def __init__(
        self,
        reader_factory: KeyValueFileReaderFactory,
        merge_executor: MergeExecutor,
        key_names: Sequence[str],
        parallelism: int | None = None,
    ):
        self.reader_factory = reader_factory
        self.merge = merge_executor
        self.key_names = set(key_names)
        # scan.parallelism: in-flight bound of the per-file decode fan-out
        self.parallelism = parallelism

    def read_split(
        self,
        files: list[DataFileMeta],
        predicate: Predicate | None = None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
        deletion_vectors: dict | None = None,
    ) -> ColumnBatch:
        """Merge-read one bucket's files. Returns the value rows (projected),
        key-sorted within each section."""
        return self.read_split_dispatch(files, predicate, projection, drop_delete, deletion_vectors)()

    def read_split_dispatch(
        self,
        files: list[DataFileMeta],
        predicate: Predicate | None = None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
        deletion_vectors: dict | None = None,
    ):
        """Phase 1 of the (possibly mesh-batched) merge-read: read the
        section inputs and dispatch their merges; returns a zero-arg
        continuation producing the final ColumnBatch. Under an active
        MeshBatchContext, the merges of every split dispatched in the same
        batch window execute as ONE shard_map over the mesh's bucket axis —
        the TPU equivalent of the reference shipping one split per task
        (MergeTreeSplitGenerator.java:38)."""
        from ..parallel.executor import current_mesh_context

        key_parts = []
        if predicate is not None:
            parts = PredicateBuilder.split_and(predicate)
            key_parts = PredicateBuilder.pick_by_fields(parts, self.key_names)
        key_filter = and_(*key_parts) if key_parts else None

        dvs = deletion_vectors or {}
        sections = IntervalPartition(files).partition()
        section_conts = []
        for section in sections:
            if len(section) == 1:
                # single sorted run: keys are unique — no merge needed; full
                # predicate pushdown is safe (reference RawFileSplitRead)
                kv_parts = _parallel_map(
                    lambda f: self._read_file(f, predicate, dvs),
                    section[0].files,
                    parallelism=self.parallelism,
                )
                kv = KVBatch.concat(kv_parts)
                section_conts.append(lambda kv=kv: kv)
            else:
                runs, seq_ascending = order_runs_for_merge(section)
                ordered_files = [f for run in runs for f in run.files]
                has_dv = any(f.file_name in dvs for f in ordered_files)
                if (
                    current_mesh_context() is None
                    and self.merge.supports_keys_only_pipeline()
                    and not has_dv
                ):
                    # single-device: overlap host decode with the device sort
                    kv = self._pipelined_dedup(ordered_files, key_filter, seq_ascending)
                    section_conts.append(lambda kv=kv: kv)
                else:
                    # mesh/DV/engine path: the per-file reads fan out over the
                    # shared pool (order preserved, so the concatenated runs
                    # and the merge output are bit-identical to serial)
                    batches = _parallel_map(
                        lambda f: self._read_file(f, key_filter, dvs),
                        ordered_files,
                        parallelism=self.parallelism,
                    )
                    kv = KVBatch.concat(batches)
                    handle = self.merge.merge_async(kv, seq_ascending=seq_ascending)
                    section_conts.append(lambda h=handle: self.merge.merge_resolve(h))

        def complete() -> ColumnBatch:
            out: list[ColumnBatch] = []
            for cont in section_conts:
                kv = cont()
                if drop_delete:
                    kv = kv.drop_deletes()
                data = kv.data
                if predicate is not None and data.num_rows:
                    mask = predicate.eval(data)
                    if not mask.all():
                        data = data.filter(mask)
                if projection is not None:
                    data = data.select(projection)
                out.append(data)
            if not out:
                schema = self.reader_factory.read_schema
                if projection is not None:
                    schema = schema.project(projection)
                return ColumnBatch.empty(schema)
            return concat_batches(out)

        return complete

    def _read_file(self, f: DataFileMeta, predicate, dvs: dict) -> KVBatch:
        """Read one file, applying its deletion vector if present. DV
        positions are absolute file row positions, so a DV'd file is read
        without row-group skipping (which would shift positions)."""
        dv = dvs.get(f.file_name)
        if dv is None:
            return self.reader_factory.read(f, predicate=predicate)
        kv = self.reader_factory.read(f, predicate=None)
        mask = ~dv.deleted_mask(kv.num_rows)
        return kv.filter(mask) if not mask.all() else kv

    def _pipelined_dedup(self, ordered_files, key_filter, seq_ascending: bool) -> KVBatch:
        """Overlap host decode with the device merge: decode just the key
        columns, dispatch the dedup kernel (async), decode the value columns
        while the device sorts, then gather. The two decode passes share the
        predicate, so their row sets are identical (datafile.read contract)."""
        key_names = [n for n in self.reader_factory.read_schema.field_names if n in self.key_names]
        rest_names = [n for n in self.reader_factory.read_schema.field_names if n not in self.key_names]
        # run stability replaces sequence comparison when seq ranges are
        # disjoint+ordered: skip decoding _SEQUENCE_NUMBER (random int64 is
        # the costliest system column) and read only _VALUE_KIND
        sys_cols = "kind" if seq_ascending else True
        heads = _parallel_map(
            lambda f: self.reader_factory.read(f, predicate=key_filter, fields=key_names, system_columns=sys_cols),
            ordered_files,
            parallelism=self.parallelism,
        )
        kv_keys = KVBatch.concat(heads)
        if kv_keys.num_rows == 0:
            return KVBatch(
                ColumnBatch.empty(self.reader_factory.read_schema),
                np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.uint8),
            )
        # file -> run offsets for key-range tiling (files of one run are
        # consecutive in ordered_files and key-sorted)
        run_offsets = [0]
        for h in heads:
            run_offsets.append(run_offsets[-1] + h.num_rows)
        handle = self.merge.dedup_select_async(kv_keys, seq_ascending, run_offsets=run_offsets)
        if rest_names:
            tails = _parallel_map(
                lambda f: self.reader_factory.read(
                    f, predicate=key_filter, fields=rest_names, system_columns=False
                ),
                ordered_files,
                parallelism=self.parallelism,
            )
            full_schema = self.reader_factory.read_schema
            cols = {}
            for name in full_schema.field_names:
                if name in self.key_names:
                    cols[name] = kv_keys.data.column(name)
                else:
                    from ..data.batch import Column

                    cols[name] = Column.concat([t.data.column(name) for t in tails])
            data = ColumnBatch(full_schema, cols)
        else:
            data = kv_keys.data
        kv = KVBatch(data, kv_keys.seq, kv_keys.kind)
        take = self.merge.dedup_resolve(handle)
        return kv.take(take)

    def read_kv(
        self, files: list[DataFileMeta], drop_delete: bool = False, deletion_vectors: dict | None = None
    ) -> KVBatch:
        """Raw merged KeyValues (used by compaction tests / changelog)."""
        dvs = deletion_vectors or {}
        sections = IntervalPartition(files).partition()
        parts: list[KVBatch] = []
        for section in sections:
            runs, seq_ascending = order_runs_for_merge(section)
            batches = [self._read_file(f, None, dvs) for run in runs for f in run.files]
            kv = KVBatch.concat(batches)
            if len(section) > 1:
                kv = self.merge.merge(kv, seq_ascending=seq_ascending)
            if drop_delete:
                kv = kv.drop_deletes()
            parts.append(kv)
        return KVBatch.concat(parts) if parts else KVBatch(
            ColumnBatch.empty(self.reader_factory.read_schema),
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.uint8),
        )
