"""Merge-on-read execution: sections -> device merge -> filtered batches.

Parity: /root/reference/paimon-core/.../operation/MergeFileSplitRead.java
(createMergeReader:246-284; the predicate split rule :184-221 — only key
filters may skip files/row-groups of overlapping sections, value filters must
run after merging so a new version can still shadow an old one) and
RawFileSplitRead.java:69 (no-merge path for single-run sections).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..data.batch import ColumnBatch, concat_batches
from ..data.predicate import Predicate, PredicateBuilder, and_
from .datafile import DataFileMeta, KeyValueFileReaderFactory
from .kv import KVBatch
from .levels import IntervalPartition
from .mergefn import MergeExecutor

__all__ = ["MergeFileSplitRead"]


class MergeFileSplitRead:
    def __init__(
        self,
        reader_factory: KeyValueFileReaderFactory,
        merge_executor: MergeExecutor,
        key_names: Sequence[str],
    ):
        self.reader_factory = reader_factory
        self.merge = merge_executor
        self.key_names = set(key_names)

    def read_split(
        self,
        files: list[DataFileMeta],
        predicate: Predicate | None = None,
        projection: Sequence[str] | None = None,
        drop_delete: bool = True,
    ) -> ColumnBatch:
        """Merge-read one bucket's files. Returns the value rows (projected),
        key-sorted within each section."""
        key_parts = []
        if predicate is not None:
            parts = PredicateBuilder.split_and(predicate)
            key_parts = PredicateBuilder.pick_by_fields(parts, self.key_names)
        key_filter = and_(*key_parts) if key_parts else None

        sections = IntervalPartition(files).partition()
        out: list[ColumnBatch] = []
        for section in sections:
            if len(section) == 1:
                # single sorted run: keys are unique — no merge needed; full
                # predicate pushdown is safe (reference RawFileSplitRead)
                kv_parts = [self.reader_factory.read(f, predicate=predicate) for f in section[0].files]
                kv = KVBatch.concat(kv_parts)
            else:
                batches = [
                    self.reader_factory.read(f, predicate=key_filter)
                    for run in section
                    for f in run.files
                ]
                kv = KVBatch.concat(batches)
                kv = self.merge.merge(kv)
            if drop_delete:
                kv = kv.drop_deletes()
            data = kv.data
            if predicate is not None and data.num_rows:
                mask = predicate.eval(data)
                if not mask.all():
                    data = data.filter(mask)
            if projection is not None:
                data = data.select(projection)
            out.append(data)
        if not out:
            schema = self.reader_factory.read_schema
            if projection is not None:
                schema = schema.project(projection)
            return ColumnBatch.empty(schema)
        return concat_batches(out)

    def read_kv(self, files: list[DataFileMeta], drop_delete: bool = False) -> KVBatch:
        """Raw merged KeyValues (used by compaction tests / changelog)."""
        sections = IntervalPartition(files).partition()
        parts: list[KVBatch] = []
        for section in sections:
            batches = [self.reader_factory.read(f) for run in section for f in run.files]
            kv = KVBatch.concat(batches)
            if len(section) > 1:
                kv = self.merge.merge(kv)
            if drop_delete:
                kv = kv.drop_deletes()
            parts.append(kv)
        return KVBatch.concat(parts) if parts else KVBatch(
            ColumnBatch.empty(self.reader_factory.read_schema),
            np.empty(0, dtype=np.int64),
            np.empty(0, dtype=np.uint8),
        )
