"""L3: the LSM core — merge-tree, compaction, manifests, snapshots, commit.

Capability parity map (reference /root/reference/paimon-core/):
  kv.py        KeyValue batch model            KeyValue.java:44
  datafile.py  DataFileMeta, file read/write   io/DataFileMeta.java:54, io/KeyValue*
  mergefn.py   merge-engine orchestration      mergetree/compact/MergeFunction.java
  levels.py    Levels/SortedRun/sections       mergetree/Levels.java:38, IntervalPartition.java:33
  writer.py    memtable + MergeTreeWriter      mergetree/MergeTreeWriter.java:57
  compact.py   universal compaction            mergetree/compact/UniversalCompaction.java:42
  manifest.py  manifest tree                   manifest/ManifestFile.java:48
  snapshot.py  snapshots + expiry              Snapshot.java:68, utils/SnapshotManager.java:55
  schema.py    schema + evolution              schema/SchemaManager.java:76, SchemaEvolutionUtil.java:54
  commit.py    CAS commit protocol             operation/FileStoreCommitImpl.java:219
  scan.py      snapshot scan planning          operation/AbstractFileStoreScan.java:221
  read.py      merge-on-read execution         operation/MergeFileSplitRead.java
"""
