"""Snapshot scan planning: manifests -> filtered file entries.

Parity: /root/reference/paimon-core/.../operation/AbstractFileStoreScan.plan()
(:221-287 — snapshot -> manifest list -> manifest reads with partition/bucket/
stat/file-index filters) and KeyValueFileStoreScan (key-stat filtering; value
filters are NOT used to skip files for merge-on-read tables because a file
missing a predicate match may still shadow older versions of the key).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Sequence

from ..data.predicate import Predicate
from ..fs import FileIO
from .manifest import FileKind, ManifestEntry, ManifestFile, ManifestList, merge_entries
from .snapshot import Snapshot, SnapshotManager

__all__ = ["ScanPlan", "FileStoreScan"]


@dataclass
class ScanPlan:
    snapshot: Snapshot | None
    entries: list[ManifestEntry] = field(default_factory=list)
    index_entries: list = field(default_factory=list)  # IndexFileEntry

    def grouped(self) -> dict[tuple, dict[int, list]]:
        """{partition: {bucket: [DataFileMeta...]}}"""
        out: dict[tuple, dict[int, list]] = {}
        for e in self.entries:
            out.setdefault(e.partition, {}).setdefault(e.bucket, []).append(e.file)
        return out

    def dv_index_for(self, partition: tuple, bucket: int) -> str | None:
        for e in self.index_entries:
            if e.kind == "DELETION_VECTORS" and e.partition == partition and e.bucket == bucket:
                return e.file_name
        return None

    def dv_indexes(self) -> dict[tuple, str]:
        """{(partition, bucket): dv index file name} for every bucket."""
        return {
            (e.partition, e.bucket): e.file_name
            for e in self.index_entries
            if e.kind == "DELETION_VECTORS"
        }


class FileStoreScan:
    def __init__(
        self,
        file_io: FileIO,
        table_path: str,
        key_names: Sequence[str],
        manifest_parallelism: int | None = None,
        cache=None,
    ):
        self.file_io = file_io
        self.table_path = table_path
        self.key_names = list(key_names)
        self.manifest_parallelism = manifest_parallelism
        # manifest object cache (utils.cache): repeated plan() calls and
        # streaming follow-ups stop re-fetching + re-decoding the snapshot,
        # manifest lists, and manifest files of unchanged history
        self.snapshot_manager = SnapshotManager(file_io, table_path, cache=cache)
        self.manifest_file = ManifestFile(file_io, f"{table_path}/manifest", cache=cache)
        self.manifest_list = ManifestList(file_io, f"{table_path}/manifest", cache=cache)
        self._snapshot_id: int | None = None
        self._kind = "all"  # all | delta | changelog
        self._partition_filter: Callable[[tuple], bool] | None = None
        self._bucket: int | None = None
        self._key_filter: Predicate | None = None
        self._value_filter: Predicate | None = None  # only safe for append tables
        self._level: int | None = None

    # ---- builder -------------------------------------------------------
    def with_snapshot(self, snapshot_id: int) -> "FileStoreScan":
        self._snapshot_id = snapshot_id
        return self

    def with_kind(self, kind: str) -> "FileStoreScan":
        assert kind in ("all", "delta", "changelog")
        self._kind = kind
        return self

    def with_partition_filter(self, fn: Callable[[tuple], bool]) -> "FileStoreScan":
        self._partition_filter = fn
        return self

    def with_bucket(self, bucket: int) -> "FileStoreScan":
        self._bucket = bucket
        return self

    def with_key_filter(self, predicate: Predicate | None) -> "FileStoreScan":
        self._key_filter = predicate
        return self

    def with_value_filter(self, predicate: Predicate | None) -> "FileStoreScan":
        self._value_filter = predicate
        return self

    def with_level(self, level: int) -> "FileStoreScan":
        self._level = level
        return self

    # ---- plan ----------------------------------------------------------
    def plan(self) -> ScanPlan:
        from ..metrics import registry, timed

        g = registry.group("scan")
        with timed(g.histogram("duration_ms")):
            plan = self._plan()
        g.counter("plans").inc()
        g.counter("resulted_table_files").inc(len(plan.entries))
        return plan

    def _read_manifests(self, metas) -> list:
        """Manifest files decode independently: scan.manifest.parallelism
        (falling back to scan.parallelism — store.new_scan resolves the
        knobs) threads them over the process-wide shared pool (reference
        ScanParallelExecutor; a pool per plan() would pay thread spawn/join
        on every small scan), order preserved and in-flight bounded."""
        if self.manifest_parallelism and self.manifest_parallelism > 1 and len(metas) > 1:
            from ..parallel.pipeline import bounded_map

            return bounded_map(
                lambda m: self.manifest_file.read(m.file_name), metas, self.manifest_parallelism
            )
        return [self.manifest_file.read(m.file_name) for m in metas]

    def _plan(self) -> ScanPlan:
        if self._snapshot_id is not None:
            snapshot = self.snapshot_manager.snapshot(self._snapshot_id)
        else:
            snapshot = self.snapshot_manager.latest_snapshot()
        if snapshot is None:
            return ScanPlan(None, [])
        if self._kind == "changelog":
            if not snapshot.changelog_manifest_list:
                return ScanPlan(snapshot, [])
            metas = self.manifest_list.read(snapshot.changelog_manifest_list)
            entries = [e for part in self._read_manifests(metas) for e in part]
        elif self._kind == "delta":
            metas = self.manifest_list.read(snapshot.delta_manifest_list)
            entries = [e for part in self._read_manifests(metas) for e in part]
            # delta scans surface ADDs only (changelog semantics come from
            # commit kind + changelog files)
            entries = [e for e in entries if e.kind == FileKind.ADD]
        else:
            metas = self.manifest_list.read(snapshot.base_manifest_list) + self.manifest_list.read(
                snapshot.delta_manifest_list
            )
            entries = merge_entries(*self._read_manifests(metas))
        entries = [e for e in entries if self._accept(e)]
        index_entries = []
        if snapshot.index_manifest:
            from .indexmanifest import read_index_manifest

            for e in read_index_manifest(self.file_io, self.table_path, snapshot.index_manifest):
                if self._partition_filter is not None and not self._partition_filter(e.partition):
                    continue
                if self._bucket is not None and e.bucket != self._bucket:
                    continue
                index_entries.append(e)
        return ScanPlan(snapshot, entries, index_entries)

    def _accept(self, e: ManifestEntry) -> bool:
        if self._partition_filter is not None and not self._partition_filter(e.partition):
            return False
        if self._bucket is not None and e.bucket != self._bucket:
            return False
        if self._level is not None and e.file.level != self._level:
            return False
        if self._key_filter is not None and not self._key_filter.test_stats(e.file.key_stats):
            return False
        if self._value_filter is not None and not self._value_filter.test_stats(e.file.value_stats):
            return False
        return True
