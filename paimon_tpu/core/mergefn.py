"""Merge-engine orchestration over the device kernel.

Parity: /root/reference/paimon-core/.../mergetree/compact/MergeFunction.java
hierarchy — DeduplicateMergeFunction, FirstRowMergeFunction,
PartialUpdateMergeFunction.java:57, AggregateMergeFunction + factories.
One MergeExecutor call is the batch equivalent of feeding every same-key group
through the reference's reset/add/getResult loop: encode keys, run the sort
plan on device, apply the engine as segment selections/reductions, and emit
one key-sorted output row per key.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch
from ..data.keys import build_string_pool, encode_key_lanes, split_int64_lanes
from ..options import CoreOptions, MergeEngine
from ..ops import (
    AggregateSpec,
    aggregate_merge,
    deduplicate_select,
    deduplicate_take,
    first_row_take,
    merge_plan,
    partial_update_takes,
)
from ..ops.aggregates import _gather_column
from ..types import RowKind, RowType, TypeRoot
from .kv import KVBatch

__all__ = ["MergeExecutor"]


def _numpy_dedup_select(lanes: np.ndarray, seq_lanes: np.ndarray | None, compress: bool | None = None) -> np.ndarray:
    """sort-engine=numpy: the pure-host oracle path (useful when no
    accelerator is attached, and as the reference implementation the device
    kernels are tested against). Lane compression applies here too — fewer
    lexsort key arrays and fewer boundary compares, same selection — with an
    all-constant key short-circuiting to the scalar winner."""
    from ..data.keys import lexsort_rows
    from ..ops.lanes import compress_key_lanes, scalar_dedup_winner

    n = lanes.shape[0]
    lanes, plan = compress_key_lanes(lanes, compress, enable_ovc=False)
    if plan is not None and lanes.shape[1] == 0:
        return scalar_dedup_winner(seq_lanes, n)
    tiebreakers = [] if seq_lanes is None else [seq_lanes[:, i] for i in range(seq_lanes.shape[1])]
    order = lexsort_rows(lanes, *tiebreakers)
    sorted_lanes = lanes[order]
    neq = (sorted_lanes[1:] != sorted_lanes[:-1]).any(axis=1)
    keep_last = np.concatenate([neq, np.ones(1, dtype=np.bool_)])
    return order[keep_last]


class MergeExecutor:
    def __init__(
        self,
        value_schema: RowType,
        key_names: Sequence[str],
        engine: MergeEngine = MergeEngine.DEDUPLICATE,
        options: CoreOptions | None = None,
    ):
        self.value_schema = value_schema
        self.key_names = list(key_names)
        self.engine = engine
        self.options = options or CoreOptions()
        self._string_keys = [
            k
            for k in self.key_names
            if value_schema.field(k).type.root in (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)
        ]
        self._user_seq = self.options.sequence_field

    @property
    def _compress(self) -> bool:
        """merge.lane-compression: the key-lane compression layer (the
        PAIMON_TPU_LANE_COMPRESSION env var overrides at the ops seam)."""
        return self.options.lane_compression

    def effective_sort_engine(self):
        """The merge backend actually used. sort-engine set on the table
        wins unconditionally (a table that explicitly chose numpy/pallas
        keeps it); then the PAIMON_TPU_SORT_ENGINE env var (the CI forcing
        knob, pattern of PAIMON_TPU_MERGE_ENGINE) pins every table that
        did not choose; otherwise the default ADAPTS to the resolved
        platform: the host lexsort path on a CPU-only backend (a single
        stable `np.lexsort` beats XLA:CPU's variadic stable sort ~3x at the
        1M-row scale), the device kernel everywhere else. The check never
        initializes a backend (ops.merge.resolved_platform_is_cpu).
        PAIMON_TPU_FORCE_DEVICE_ENGINE=1 pins the device kernel so the test
        suite exercises the dispatch path on its virtual-CPU mesh."""
        import os

        from ..options import CoreOptions, SortEngine

        if self.options.options.contains(CoreOptions.SORT_ENGINE):
            return SortEngine(self.options.sort_engine)
        env = os.environ.get("PAIMON_TPU_SORT_ENGINE", "").strip().lower()
        if env:
            return SortEngine(env)
        if os.environ.get("PAIMON_TPU_FORCE_DEVICE_ENGINE", "") == "1":
            return SortEngine(self.options.sort_engine)
        from ..ops.merge import resolved_platform_is_cpu

        if resolved_platform_is_cpu():
            return SortEngine.NUMPY
        return SortEngine(self.options.sort_engine)

    def _engine_str(self) -> str:
        """The ops-layer engine tag for the sorted_segments seam: 'pallas'
        routes every merge kernel's sort+boundary preamble through the fused
        pallas kernels; everything else is the stock XLA path. (The numpy
        engine never reaches a device kernel — callers branch before.)"""
        from ..options import SortEngine

        return "pallas" if self.effective_sort_engine() == SortEngine.PALLAS else "xla"

    def _key_lanes(self, kv: KVBatch) -> np.ndarray:
        from ..data.keys import encode_key_lanes_with_pools

        return encode_key_lanes_with_pools(kv.data, self.key_names)

    def _lanes(self, kv: KVBatch, seq_ascending: bool) -> tuple[np.ndarray, np.ndarray | None]:
        return self._key_lanes(kv), self._seq_lanes(kv, seq_ascending)

    def _seq_lanes(self, kv: KVBatch, seq_ascending: bool) -> np.ndarray | None:
        seq_parts = []
        if self._user_seq:
            # user-defined sequence fields order before the system seqno
            # (reference: MergeSorter orders by (key, udsSeq, seqNumber))
            from ..data.keys import exact_string_pool

            useq_pools = {
                f: exact_string_pool([kv.data.column(f)])
                for f in self._user_seq
                if kv.data.schema.field(f).type.root in (TypeRoot.CHAR, TypeRoot.VARCHAR)
            }
            seq_parts.append(encode_key_lanes(kv.data, self._user_seq, useq_pools))
        if not seq_ascending:
            # explicit seqno lanes only when input order doesn't already
            # encode them (stability of the device sort covers the rest)
            hi, lo = split_int64_lanes(kv.seq)
            seq_parts.append(np.stack([hi, lo], axis=1))
        return np.concatenate(seq_parts, axis=1) if seq_parts else None

    @staticmethod
    def _strictly_increasing(lanes: np.ndarray) -> bool:
        """O(n) host check: are the key tuples strictly ascending? Compare
        lane-wise: row i < row i+1 lexicographically for every i."""
        if lanes.shape[0] <= 1:
            return True
        a, b = lanes[:-1], lanes[1:]
        k = lanes.shape[1]
        lt = np.zeros(len(a), dtype=np.bool_)
        eq = np.ones(len(a), dtype=np.bool_)
        for i in range(k):
            lt |= eq & (a[:, i] < b[:, i])
            eq &= a[:, i] == b[:, i]
        return bool(lt.all())

    def _plan(self, kv: KVBatch, seq_ascending: bool = False):
        lanes, seq_lanes = self._lanes(kv, seq_ascending)
        return merge_plan(lanes, seq_lanes, compress=self._compress, engine=self._engine_str())

    def merge(self, kv: KVBatch, seq_ascending: bool = False) -> KVBatch:
        """One output row per key, key-sorted. Dedup keeps the winning row's
        RowKind (a -D survives compaction until the top level); partial-update
        and aggregation emit +I rows.

        seq_ascending=True asserts that rows with equal keys appear in
        ascending sequence-number order in the input (true for memtable
        flushes and for runs with disjoint seq ranges concatenated in seq
        order) — the kernel then skips uploading sequence lanes entirely.
        """
        return self.merge_resolve(self.merge_async(kv, seq_ascending))

    def merge_async(self, kv: KVBatch, seq_ascending: bool = False):
        """Dispatch half of merge(). When a MeshBatchContext is active, the
        bucket's merge becomes a job — every job dispatched in the batch
        window runs in one shard_map over the mesh at the first resolve;
        without a context the merge computes eagerly inside the handle. One
        copy of the preamble (ignore-delete, sorted-unique shortcut, lane
        encoding) serves both paths, so mesh and single-device execution
        cannot diverge. Resolve with merge_resolve()."""
        from ..options import SortEngine
        from ..parallel.executor import current_mesh_context

        ctx = current_mesh_context()
        if kv.num_rows == 0:
            return ("sync", kv)
        if self.options.ignore_delete:
            keep = kv.kind != int(RowKind.DELETE)
            if not keep.all():
                kv = kv.filter(keep)
                if kv.num_rows == 0:
                    return ("sync", kv)
        if self.engine == MergeEngine.DEDUPLICATE:
            lanes = self._key_lanes(kv)
            if self._strictly_increasing(lanes):
                # already key-sorted with unique keys (bulk loads, replayed
                # sorted runs): dedup is the identity — skip the device trip
                # (sequence lanes are never built on this path)
                return ("sync", kv)
            seq_lanes = self._seq_lanes(kv, seq_ascending)
            engine = self.effective_sort_engine()
            if engine == SortEngine.NUMPY:
                return ("sync", kv.take(_numpy_dedup_select(lanes, seq_lanes, self._compress)))
            if ctx is not None:
                if getattr(ctx, "plans_globally", False):
                    # MeshExecutor: submit RAW lanes — compression is decided
                    # ONCE per family batch from stats reduced over every
                    # shard (ops.lanes.plan_lanes_global), so all shards of
                    # one shard_map agree on packed widths (ISSUE 7 fix)
                    from ..ops.lanes import resolve_compress

                    return (
                        "dedup",
                        ctx,
                        ctx.submit_dedup(lanes, seq_lanes, compress=resolve_compress(self._compress)),
                        kv,
                    )
                # legacy MeshBatchContext: compress before submit (per-job
                # plans are safe there — jobs never share a comparator)
                from ..ops.lanes import compress_key_lanes

                cl, _ = compress_key_lanes(lanes, self._compress, enable_ovc=False)
                return ("dedup", ctx, ctx.submit_dedup(cl, seq_lanes), kv)
            backend = "pallas" if engine == SortEngine.PALLAS else "xla"
            from ..ops.merge import deduplicate_resolve, deduplicate_select_async

            return (
                "sync",
                kv.take(
                    deduplicate_resolve(
                        deduplicate_select_async(lanes, seq_lanes, backend=backend, compress=self._compress)
                    )
                ),
            )
        lanes, seq_lanes = self._lanes(kv, seq_ascending)
        engine = self.effective_sort_engine()
        if ctx is not None and engine != SortEngine.NUMPY:
            if getattr(ctx, "plans_globally", False):
                from ..ops.lanes import resolve_compress

                return (
                    "plan",
                    ctx,
                    ctx.submit_plan(lanes, seq_lanes, compress=resolve_compress(self._compress)),
                    kv,
                )
            from ..ops.lanes import compress_key_lanes

            cl, _ = compress_key_lanes(lanes, self._compress, enable_ovc=False)
            return ("plan", ctx, ctx.submit_plan(cl, seq_lanes), kv)
        if engine != SortEngine.NUMPY:
            # single-device fast paths: sort + segment + engine selection in
            # ONE kernel call (no plan download, no per-field round trips)
            if self.engine == MergeEngine.PARTIAL_UPDATE and not self._sequence_groups():
                return ("sync", self._partial_update_fused(kv, lanes, seq_lanes))
            if self.engine == MergeEngine.AGGREGATE:
                from ..ops.aggregates import fused_routable

                fields = [f for f in self.value_schema.fields if f.name not in self.key_names]
                specs = [self._agg_spec(f.name) for f in fields]
                cols = [kv.data.column(f.name) for f in fields]
                if fused_routable(specs, cols):
                    return ("sync", self._aggregate_fused(kv, lanes, seq_lanes, fields, specs, cols))
        return (
            "sync",
            self._merge_with_plan(
                kv, merge_plan(lanes, seq_lanes, compress=self._compress, engine=self._engine_str())
            ),
        )

    def merge_resolve(self, handle) -> KVBatch:
        tag = handle[0]
        if tag == "sync":
            return handle[1]
        _, ctx, job_id, kv = handle
        if tag == "dedup":
            return kv.take(ctx.result(job_id))
        return self._merge_with_plan(kv, ctx.result(job_id))

    def supports_keys_only_pipeline(self) -> bool:
        """True when merge needs only (key cols, seq, kind) to pick winners —
        lets the read path dispatch the kernel before value columns decode."""
        return self.engine == MergeEngine.DEDUPLICATE and not self.options.ignore_delete and not self._user_seq

    def dedup_select_async(self, kv_keys: KVBatch, seq_ascending: bool, run_offsets=None):
        """kv_keys carries only the key columns. Returns an opaque handle.
        With run_offsets and no explicit seq lanes, dispatches key-range tiles
        so transfers of one tile overlap the device sort of another. On the
        host engine (explicit or platform-adaptive) the select runs
        synchronously — same handle contract, no device round trip."""
        lanes, seq_lanes = self._lanes(kv_keys, seq_ascending)
        from ..options import SortEngine

        engine = self.effective_sort_engine()
        if engine == SortEngine.NUMPY:
            return ("numpy", _numpy_dedup_select(lanes, seq_lanes, self._compress))
        from ..ops.merge import deduplicate_select_async, deduplicate_tiled_dispatch

        backend = "pallas" if engine == SortEngine.PALLAS else "xla"
        if seq_lanes is None and run_offsets is not None:
            tile_rows = self.options.options.get(CoreOptions.MERGE_READ_BATCH_ROWS)
            # the tiled dispatcher owns the compression seam (one plan per
            # merge, shared by every tile) and the all-constant fast path
            return (
                "tiled",
                deduplicate_tiled_dispatch(
                    lanes, run_offsets, tile_rows, backend=backend, compress=self._compress
                ),
            )
        return ("single", deduplicate_select_async(lanes, seq_lanes, backend=backend, compress=self._compress))

    @staticmethod
    def dedup_resolve(handle) -> np.ndarray:
        tag, h = handle
        if tag == "numpy":
            return h
        from ..ops.merge import deduplicate_resolve, deduplicate_resolve_tiled

        return deduplicate_resolve_tiled(h) if tag == "tiled" else deduplicate_resolve(h)

    def _merge_with_plan(self, kv: KVBatch, plan) -> KVBatch:
        if self.engine == MergeEngine.FIRST_ROW:
            if np.isin(kv.kind, (int(RowKind.UPDATE_BEFORE), int(RowKind.DELETE))).any():
                raise ValueError("first-row merge engine accepts only +I/+U records")
            return kv.take(first_row_take(plan))

        last_take = plan.perm[plan.keep_last & plan.valid_sorted]
        out_seq = kv.seq.take(last_take)

        if self.engine == MergeEngine.PARTIAL_UPDATE:
            return self._partial_update(kv, plan, last_take, out_seq)
        if self.engine == MergeEngine.AGGREGATE:
            return self._aggregate(kv, plan, last_take, out_seq)
        raise ValueError(f"unknown merge engine {self.engine}")

    # ---- partial update -------------------------------------------------
    def _sequence_groups(self) -> dict[str, list[str]]:
        """{seq-column: [fields it governs]} from fields.<col>.sequence-group
        options (reference PartialUpdateMergeFunction sequence groups)."""
        groups: dict[str, list[str]] = {}
        for key, value in self.options.options._data.items():
            if key.startswith("fields.") and key.endswith(".sequence-group"):
                seq_col = key[len("fields.") : -len(".sequence-group")]
                groups[seq_col] = [s.strip() for s in str(value).split(",")]
        return groups

    def _check_partial_update_deletes(self, kv: KVBatch, remove_on_delete: bool) -> None:
        has_delete = np.isin(kv.kind, (int(RowKind.DELETE), int(RowKind.UPDATE_BEFORE))).any()
        if has_delete and not remove_on_delete:
            raise ValueError(
                "partial-update cannot handle -U/-D records; set "
                "'partial-update.remove-record-on-delete' or 'ignore-delete'"
            )

    def _partial_update_fused(self, kv: KVBatch, lanes, seq_lanes) -> KVBatch:
        """Single-call partial-update (no sequence groups): the fused kernel
        returns per-field sources + existence + winners in one device trip."""
        from ..ops.merge import fused_partial_update

        remove_on_delete = self.options.options.get(CoreOptions.PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE)
        self._check_partial_update_deletes(kv, remove_on_delete)
        fields = [f for f in self.value_schema.fields if f.name not in self.key_names]
        field_valid = (
            np.stack([kv.data.column(f.name).valid_mask() for f in fields])
            if fields
            else np.zeros((0, kv.num_rows), np.bool_)
        )
        src, exists, last_take = fused_partial_update(
            lanes,
            seq_lanes,
            field_valid,
            kv.kind,
            remove_record_on_delete=remove_on_delete,
            compress=self._compress,
            engine=self._engine_str(),
        )
        cols: dict[str, Column] = {}
        for k in self.key_names:
            cols[k] = kv.data.column(k).take(last_take)
        for fi, f in enumerate(fields):
            cols[f.name] = _gather_column(kv.data.column(f.name), src[fi])
        data = ColumnBatch(self.value_schema, cols)
        # without remove-on-delete every row is +I/+U (checked above), so
        # every segment exists; with it, vanished keys stay as -D rows
        kind = np.where(exists, int(RowKind.INSERT), int(RowKind.DELETE)).astype(np.uint8)
        return KVBatch(data, kv.seq.take(last_take), kind)

    def _aggregate_fused(self, kv: KVBatch, lanes, seq_lanes, fields, specs, cols_in) -> KVBatch:
        """Single-call aggregation: every column's segment reduction runs in
        the same kernel as the sort."""
        from ..ops.aggregates import fused_aggregate

        agg_cols, last_take = fused_aggregate(
            lanes, seq_lanes, cols_in, specs, kv.kind, compress=self._compress, engine=self._engine_str()
        )
        cols: dict[str, Column] = {}
        for k in self.key_names:
            cols[k] = kv.data.column(k).take(last_take)
        for f, c in zip(fields, agg_cols):
            cols[f.name] = c
        data = ColumnBatch(self.value_schema, cols)
        kind = np.full(len(last_take), int(RowKind.INSERT), dtype=np.uint8)
        return KVBatch(data, kv.seq.take(last_take), kind)

    def _partial_update(self, kv: KVBatch, plan, last_take, out_seq) -> KVBatch:
        remove_on_delete = self.options.options.get(CoreOptions.PARTIAL_UPDATE_REMOVE_RECORD_ON_DELETE)
        self._check_partial_update_deletes(kv, remove_on_delete)
        groups = self._sequence_groups()
        grouped_fields = {f for fields in groups.values() for f in fields} | set(groups)
        non_key = [f for f in self.value_schema.fields if f.name not in self.key_names]
        default_fields = [f for f in non_key if f.name not in grouped_fields]
        field_valid = (
            np.stack([kv.data.column(f.name).valid_mask() for f in default_fields])
            if default_fields
            else np.zeros((0, kv.num_rows), np.bool_)
        )
        src, exists = partial_update_takes(plan, field_valid, kv.kind, remove_record_on_delete=remove_on_delete)
        cols: dict[str, Column] = {}
        for k in self.key_names:
            cols[k] = kv.data.column(k).take(last_take)
        for fi, f in enumerate(default_fields):
            cols[f.name] = _gather_column(kv.data.column(f.name), src[fi])
        # sequence groups: each group's fields are taken atomically from the
        # row with the highest (group seq, system seq) whose group seq is
        # non-null — ordering by the group's own sequence column, not arrival
        for seq_col, fields in groups.items():
            cols.update(self._group_take(kv, seq_col, fields))
        data = ColumnBatch(self.value_schema, cols)
        kind = np.where(exists, int(RowKind.INSERT), int(RowKind.DELETE)).astype(np.uint8)
        out = KVBatch(data, out_seq, kind)
        if not exists.all() and not remove_on_delete:
            out = out.filter(exists)
        return out

    def _group_take(self, kv: KVBatch, seq_col: str, fields: Sequence[str]) -> dict[str, Column]:
        from ..ops.aggregates import _pick_fn
        from ..ops.merge import pad_to

        import jax.numpy as jnp

        key_lanes = self._key_lanes(kv)
        # order: (key, group seq, system seq); null group seq sorts first and
        # is excluded from candidacy
        gcol = kv.data.column(seq_col)
        g_valid = gcol.valid_mask()
        root = kv.data.schema.field(seq_col).type.root
        from ..types import TypeRoot

        gpool = None
        if root in (TypeRoot.CHAR, TypeRoot.VARCHAR):
            gpool = {seq_col: build_string_pool([gcol.values[g_valid]])}
        g_lanes = self._lanes_nullsafe(gcol, root, gpool, seq_col)
        hi, lo = split_int64_lanes(kv.seq)
        seq_lanes = np.concatenate([g_lanes, np.stack([hi, lo], axis=1)], axis=1)
        gplan = merge_plan(key_lanes, seq_lanes, compress=self._compress, engine=self._engine_str())
        candidate = g_valid & np.isin(kv.kind, (int(RowKind.INSERT), int(RowKind.UPDATE_AFTER)))
        src = _pick_fn(True)(
            jnp.asarray(gplan.perm), jnp.asarray(gplan.seg_id), jnp.asarray(pad_to(candidate, gplan.m, False))
        )
        src = np.asarray(src)[: gplan.num_segments]
        out = {}
        out[seq_col] = _gather_column(kv.data.column(seq_col), src)
        default_fn = self.options.options.get(CoreOptions.AGGREGATE_DEFAULT_FUNC)
        for name in fields:
            # per-field aggregators INSIDE a sequence group aggregate over the
            # group's ordering (reference PartialUpdateMergeFunction supports
            # fields.<f>.aggregate-function within sequence groups, falling
            # back to fields.default-aggregate-function); fields without
            # either take the winning row's snapshot value
            fn = self.options.field_option(name, "aggregate-function") or default_fn
            if fn is not None:
                col = kv.data.column(name)
                # rows whose group sequence is null do not participate in the
                # group at all (reference isEmptySequenceGroup :150) — mask
                # them out of the aggregation via validity
                if not g_valid.all():
                    col = Column(col.values, col.valid_mask() & g_valid)
                out[name] = aggregate_merge(gplan, col, self._agg_spec(name), kv.kind)
            else:
                out[name] = _gather_column(kv.data.column(name), src)
        return out

    @staticmethod
    def _lanes_nullsafe(col: Column, root, pool, name: str) -> np.ndarray:
        """Lane-encode a possibly-null sequence column (nulls get the minimal
        lane value, so they lose every comparison)."""
        from ..data.keys import _encode_column

        valid = col.valid_mask()
        values = col.values
        if values.dtype == np.dtype(object):
            ranks = np.zeros(len(values), dtype=np.uint32)
            if valid.any():
                p = pool[name] if pool else np.unique(values[valid])
                # ranks offset by 1 so nulls (0) sort below every real value
                ranks[valid] = np.searchsorted(p, values[valid]).astype(np.uint32) + 1
            return ranks.reshape(-1, 1)
        filled = values.copy()
        filled[~valid] = 0
        lanes = np.stack(_encode_column(filled, root, None), axis=1)
        lanes[~valid] = 0
        return lanes

    # ---- aggregation ----------------------------------------------------
    def _agg_spec(self, field_name: str) -> AggregateSpec:
        fn = self.options.field_option(field_name, "aggregate-function")
        if fn is None:
            fn = self.options.options.get(CoreOptions.AGGREGATE_DEFAULT_FUNC) or "last_non_null_value"
        ignore_retract = (self.options.field_option(field_name, "ignore-retract") or "false").lower() == "true"
        delim = self.options.field_option(field_name, "list-agg-delimiter") or ","
        distinct = (self.options.field_option(field_name, "distinct") or "false").lower() == "true"
        nested_key = tuple(
            s.strip() for s in (self.options.field_option(field_name, "nested-key") or "").split(",") if s.strip()
        )
        return AggregateSpec(fn, ignore_retract, delim, distinct, nested_key)

    def _aggregate(self, kv: KVBatch, plan, last_take, out_seq) -> KVBatch:
        cols: dict[str, Column] = {}
        for k in self.key_names:
            cols[k] = kv.data.column(k).take(last_take)
        for f in self.value_schema.fields:
            if f.name in self.key_names:
                continue
            cols[f.name] = aggregate_merge(plan, kv.data.column(f.name), self._agg_spec(f.name), kv.kind)
        data = ColumnBatch(self.value_schema, cols)
        kind = np.full(len(last_take), int(RowKind.INSERT), dtype=np.uint8)
        return KVBatch(data, out_seq, kind)
