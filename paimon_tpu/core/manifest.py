"""The manifest metadata tree.

Parity: /root/reference/paimon-core/.../manifest/ — ManifestEntry (ADD/DELETE
of a DataFileMeta at (partition, bucket)), ManifestFile.java:48,
ManifestFileMeta.java:54 (+ merge() small-manifest compaction at commit),
ManifestList, ManifestCommittable (per-checkpoint committable), and
sink/CommitMessage. Storage is zstd-compressed JSON-lines (the reference uses
Avro; the logical content is identical — metadata is host-side and tiny
relative to data, so the container format is not a hot path).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Iterable, Sequence

from ..fs import FileIO
from ..utils import dumps, loads, new_file_name
from ..utils.compression import ZSTD_MAGIC, zstd_compress, zstd_decompress
from .datafile import DataFileMeta

if TYPE_CHECKING:
    from ..utils.cache import ByteBudgetLRU

__all__ = [
    "FileKind",
    "ManifestEntry",
    "ManifestFileMeta",
    "ManifestFile",
    "ManifestList",
    "CommitMessage",
    "ManifestCommittable",
    "merge_entries",
]


class FileKind(int, enum.Enum):
    ADD = 0
    DELETE = 1


@dataclass(frozen=True)
class ManifestEntry:
    kind: FileKind
    partition: tuple
    bucket: int
    total_buckets: int
    file: DataFileMeta

    def identifier(self) -> tuple:
        return (self.partition, self.bucket, self.file.level, self.file.file_name)

    def to_dict(self) -> dict:
        return {
            "kind": int(self.kind),
            "partition": list(self.partition),
            "bucket": self.bucket,
            "totalBuckets": self.total_buckets,
            "file": self.file.to_dict(),
        }

    @staticmethod
    def from_dict(d: dict) -> "ManifestEntry":
        return ManifestEntry(
            FileKind(d["kind"]), tuple(d["partition"]), d["bucket"], d["totalBuckets"], DataFileMeta.from_dict(d["file"])
        )


@dataclass(frozen=True)
class ManifestFileMeta:
    file_name: str
    file_size: int
    num_added_files: int
    num_deleted_files: int
    schema_id: int

    def to_dict(self) -> dict:
        return {
            "fileName": self.file_name,
            "fileSize": self.file_size,
            "numAddedFiles": self.num_added_files,
            "numDeletedFiles": self.num_deleted_files,
            "schemaId": self.schema_id,
        }

    @staticmethod
    def from_dict(d: dict) -> "ManifestFileMeta":
        return ManifestFileMeta(d["fileName"], d["fileSize"], d["numAddedFiles"], d["numDeletedFiles"], d["schemaId"])


_AVRO_MAGIC = b"Obj\x01"


class _JsonlZst:
    """Manifest container io. The store's native format is zstd-compressed
    JSON-lines; `manifest.format=avro` switches WRITES to the reference's
    Avro layout (interop.manifest_codec) and READS always sniff the magic
    bytes, so mixed-format histories (option flipped mid-life, or a table
    laid out by the reference) read transparently."""

    def __init__(self, file_io: FileIO, directory: str, cache: "ByteBudgetLRU | None" = None):
        self.file_io = file_io
        self.directory = directory
        # decoded-object cache (utils.cache manifest cache): manifest files
        # are immutable once written, so decoded entry lists are cached
        # process-wide keyed by full path. None = this accessor bypasses it.
        self.cache = cache
        self._table_cfg = None  # lazy (format, resolver, compression)

    def _config(self):
        """(manifest_format, resolver) from the owning table's schemas —
        self-provisioned so every construction site keeps working. Failures
        are NOT cached (a transient IO error must not downgrade an avro table
        to jsonl writes for the object's lifetime)."""
        if self._table_cfg is None:
            from ..interop.manifest_codec import StatsContext
            from .schema import SchemaManager

            table_path = self.directory.rsplit("/", 1)[0]
            sm = SchemaManager(self.file_io, table_path)
            ts = sm.latest()  # IO errors propagate; None = no table schema
            if ts is None:
                return ("jsonl", None, "default")
            fmt = str(ts.options.get("manifest.format", "jsonl")).lower()
            compression = str(ts.options.get("manifest.compression", "default")).lower()
            latest_ctx = StatsContext.from_table_schema(ts)
            cache: dict[int, "StatsContext"] = {ts.id: latest_ctx}

            def resolver(schema_id: int):
                # positional BinaryRow stats decode under the schema that
                # WROTE them, not the latest (schema evolution)
                if schema_id not in cache:
                    try:
                        old = sm.schema(schema_id)
                        cache[schema_id] = StatsContext.from_table_schema(old)
                    except Exception:
                        cache[schema_id] = latest_ctx
                return cache[schema_id]

            self._table_cfg = (fmt, resolver, compression)
        return self._table_cfg

    def _write_payload(self, name: str, data: bytes, track: list[str] | None = None) -> int:
        """Publish a manifest payload ATOMICALLY (tmp sibling + rename): a
        writer dying mid-write can never leave a half-written file at the
        final name, and a retried write stages a fresh tmp instead of
        tripping over its own partial first attempt. `track` records `name`
        BEFORE any byte lands, so an aborting commit can clean both the file
        and any torn tmp sibling (FileStoreCommit._cleanup)."""
        if track is not None:
            track.append(name)
        if not self.file_io.try_atomic_write(f"{self.directory}/{name}", data):
            # uuid file names never collide; losing this CAS means the
            # namespace is being re-written underneath us
            raise OSError(f"manifest {name} unexpectedly already exists")
        return len(data)

    def _write_lines(self, name: str, dicts: Iterable[dict], track: list[str] | None = None) -> int:
        raw = "\n".join(dumps(d) for d in dicts).encode()
        _, _, compression = self._config()
        data = raw if compression == "none" else zstd_compress(raw, level=3)
        return self._write_payload(name, data, track)

    def _read_raw(self, name: str) -> bytes:
        return self.file_io.read_bytes(f"{self.directory}/{name}")

    def _read_lines_from(self, data: bytes) -> list[dict]:
        # sniff: zstd magic, else plain jsonl (manifest.compression=none)
        if data[:4] == ZSTD_MAGIC:
            raw = zstd_decompress(data)
        else:
            raw = data
        return [loads(line) for line in raw.decode().splitlines() if line]

    def _cached_read(self, kind: str, name: str, decode):
        """Decode-once manifest reads: cache stores an immutable tuple keyed
        by (kind, full path); callers get a fresh list so accidental caller
        mutation can never poison the cache."""
        if self.cache is None or not self.cache.enabled:
            return decode()
        path = f"{self.directory}/{name}"
        key = (kind, path)
        cached = self.cache.get(key)
        if cached is not None:
            return list(cached)
        out = decode()
        # weight ≈ decoded footprint: entries dominate; dicts/dataclasses of
        # a manifest entry run a few hundred bytes each
        self.cache.put(key, tuple(out), weight=max(len(out) * 512, 256), file_id=path)
        return list(out)

    def delete(self, name: str) -> None:
        self.file_io.delete(f"{self.directory}/{name}")
        if self.cache is not None:
            self.cache.invalidate_file(f"{self.directory}/{name}")


class ManifestFile(_JsonlZst):
    """Reads/writes manifest files (lists of ManifestEntry)."""

    def write(
        self, entries: Sequence[ManifestEntry], schema_id: int, track: list[str] | None = None
    ) -> ManifestFileMeta:
        name = new_file_name("manifest")
        fmt, resolver, compression = self._config()
        if fmt == "avro" and resolver is not None:
            from ..interop.manifest_codec import write_entries_avro

            data = write_entries_avro(entries, resolver, codec="null" if compression == "none" else "deflate")
            size = self._write_payload(name, data, track)
        else:
            size = self._write_lines(name, (e.to_dict() for e in entries), track)
        added = sum(1 for e in entries if e.kind == FileKind.ADD)
        return ManifestFileMeta(name, size, added, len(entries) - added, schema_id)

    def read(self, name: str) -> list[ManifestEntry]:
        return self._cached_read("manifest", name, lambda: self._decode(name))

    def _decode(self, name: str) -> list[ManifestEntry]:
        data = self._read_raw(name)
        if data[:4] == _AVRO_MAGIC:
            from ..interop.manifest_codec import read_entries_avro

            _, resolver, _ = self._config()
            if resolver is None:
                raise ValueError(f"avro manifest {name} needs the table schema for decoding")
            return read_entries_avro(data, resolver)
        return [ManifestEntry.from_dict(d) for d in self._read_lines_from(data)]


class ManifestList(_JsonlZst):
    """Reads/writes manifest lists (lists of ManifestFileMeta)."""

    def write(self, metas: Sequence[ManifestFileMeta], track: list[str] | None = None) -> str:
        name = new_file_name("manifest-list")
        fmt, resolver, compression = self._config()
        if fmt == "avro" and resolver is not None:
            from ..interop.manifest_codec import write_metas_avro

            self._write_payload(
                name,
                write_metas_avro(metas, resolver, codec="null" if compression == "none" else "deflate"),
                track,
            )
        else:
            self._write_lines(name, (m.to_dict() for m in metas), track)
        return name

    def read(self, name: str) -> list[ManifestFileMeta]:
        return self._cached_read("manifest-list", name, lambda: self._decode(name))

    def _decode(self, name: str) -> list[ManifestFileMeta]:
        data = self._read_raw(name)
        if data[:4] == _AVRO_MAGIC:
            from ..interop.manifest_codec import read_metas_avro

            return read_metas_avro(data)
        return [ManifestFileMeta.from_dict(d) for d in self._read_lines_from(data)]


def merge_entries(*entry_lists: Iterable[ManifestEntry]) -> list[ManifestEntry]:
    """Apply DELETE entries against ADDs in order (reference
    FileEntry.mergeEntries): the live set is ADDs not later DELETEd."""
    live: dict[tuple, ManifestEntry] = {}
    for entries in entry_lists:
        for e in entries:
            key = e.identifier()
            if e.kind == FileKind.ADD:
                live[key] = e
            else:
                live.pop(key, None)
    return list(live.values())


def merge_entries_keep_deletes(*entry_lists: Iterable[ManifestEntry]) -> list[ManifestEntry]:
    """Like merge_entries, but a DELETE whose ADD is *outside* the merged set
    survives — required when compacting a subset of manifests, else the DELETE
    is lost and the ADD in an untouched manifest resurrects a dead file
    (reference ManifestFileMeta.merge keeps unmatched deletes the same way)."""
    live: dict[tuple, ManifestEntry] = {}
    deletes: dict[tuple, ManifestEntry] = {}
    for entries in entry_lists:
        for e in entries:
            key = e.identifier()
            if e.kind == FileKind.ADD:
                live[key] = e
            elif key in live:
                live.pop(key)  # add+delete cancel within the merged set
            else:
                deletes[key] = e
    return list(deletes.values()) + list(live.values())


@dataclass
class CommitMessage:
    """Per-(partition, bucket) file changes from one writer
    (reference table/sink/CommitMessageImpl)."""

    partition: tuple
    bucket: int
    total_buckets: int
    new_files: list[DataFileMeta] = field(default_factory=list)
    compact_before: list[DataFileMeta] = field(default_factory=list)
    compact_after: list[DataFileMeta] = field(default_factory=list)
    changelog_files: list[DataFileMeta] = field(default_factory=list)  # input producer (append phase)
    compact_changelog_files: list[DataFileMeta] = field(default_factory=list)  # full-compaction producer
    new_index_files: list = field(default_factory=list)  # IndexFileEntry

    def is_empty(self) -> bool:
        return not (
            self.new_files
            or self.compact_before
            or self.compact_after
            or self.changelog_files
            or self.compact_changelog_files
            or self.new_index_files
        )

    def to_dict(self) -> dict:
        """Wire form for shipping to a remote committer (the cluster
        coordinator commits on behalf of its workers — the reference's
        serializable sink/CommitMessage crossing the Flink network stack)."""
        return {
            "partition": list(self.partition),
            "bucket": self.bucket,
            "totalBuckets": self.total_buckets,
            "newFiles": [f.to_dict() for f in self.new_files],
            "compactBefore": [f.to_dict() for f in self.compact_before],
            "compactAfter": [f.to_dict() for f in self.compact_after],
            "changelogFiles": [f.to_dict() for f in self.changelog_files],
            "compactChangelogFiles": [f.to_dict() for f in self.compact_changelog_files],
            "newIndexFiles": [e.to_dict() for e in self.new_index_files],
        }

    @staticmethod
    def from_dict(d: dict) -> "CommitMessage":
        from .deletionvectors import IndexFileEntry

        return CommitMessage(
            partition=tuple(d["partition"]),
            bucket=d["bucket"],
            total_buckets=d["totalBuckets"],
            new_files=[DataFileMeta.from_dict(f) for f in d.get("newFiles", ())],
            compact_before=[DataFileMeta.from_dict(f) for f in d.get("compactBefore", ())],
            compact_after=[DataFileMeta.from_dict(f) for f in d.get("compactAfter", ())],
            changelog_files=[DataFileMeta.from_dict(f) for f in d.get("changelogFiles", ())],
            compact_changelog_files=[
                DataFileMeta.from_dict(f) for f in d.get("compactChangelogFiles", ())
            ],
            new_index_files=[IndexFileEntry.from_dict(e) for e in d.get("newIndexFiles", ())],
        )


@dataclass
class ManifestCommittable:
    """Everything one commit needs (reference manifest/ManifestCommittable:
    commitIdentifier, watermark, logOffsets, commit messages)."""

    commit_identifier: int
    watermark: int | None = None
    log_offsets: dict[int, int] = field(default_factory=dict)
    messages: list[CommitMessage] = field(default_factory=list)
    # set by filter_committed on crash replay: the APPEND snapshot already
    # landed, only the COMPACT phase is outstanding
    skip_append: bool = False
