"""Parquet read/write over pyarrow, with row-group predicate skipping and an
optional native page-decode backend.

Parity: /root/reference/paimon-format/.../parquet/ParquetReaderFactory.java:68
(vectorized batch decode, row-group filtering via FilterCompat) and
ParquetRowDataWriter. Two read decoders sit behind one `read()`:

  * arrow (default)  — the C++ arrow reader decodes columns into numpy
    buffers; row-group pruning reuses Predicate.test_stats fed from parquet
    footer statistics;
  * native           — paimon_tpu.decode: thrift-parsed footer/pages,
    vectorized RLE/dict/delta kernels, and compressed-domain predicate
    pushdown that expands only surviving pages. Selected per table via
    `format.parquet.decoder = native`; files needing features outside the
    native envelope (nested schemas, exotic encodings) fall back to arrow
    per file (counter decode.files_fallback).

Two write encoders sit behind one `write()` the same way:

  * arrow (default)  — ColumnBatch.to_arrow (per-column pa.array object
    conversion) into pq.write_table;
  * native           — paimon_tpu.encode: vectorized PLAIN/RLE/DELTA/
    dictionary kernels writing pages straight from columnar arrays, with
    dictionary pages consuming the merge path's string pools directly.
    Selected per table via `format.parquet.encoder = native`; unsupported
    shapes fall back to arrow per file (counter encode.files_fallback),
    never per table.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from ..data.batch import ColumnBatch
from ..data.predicate import FieldStats, Predicate
from ..fs import FileIO
from ..types import RowType, TypeRoot
from . import FileFormat, register_format

_OBJ_DTYPE = np.dtype(object)
_STRING_ROOTS = (TypeRoot.CHAR, TypeRoot.VARCHAR, TypeRoot.BINARY, TypeRoot.VARBINARY)


class ParquetFormat(FileFormat):
    identifier = "parquet"

    def __init__(self, decoder: str = "arrow", encoder: str = "arrow"):
        self.decoder = decoder
        self.encoder = encoder
        # merge.dict-domain: both decoders return dictionary-encoded
        # string/bytes chunks as code-backed columns (PAIMON_TPU_DICT_DOMAIN
        # env overrides, same rollout pattern as the decoder/encoder knobs)
        from ..ops.dicts import resolve_dict_domain, resolve_pool_limit

        self.dict_domain = resolve_dict_domain(None)
        self.pool_limit = resolve_pool_limit(None)

    def configure(self, format_options: dict | None) -> "ParquetFormat":
        from ..ops.dicts import resolve_dict_domain, resolve_pool_limit

        opts = format_options or {}
        d = opts.get("format.parquet.decoder")
        if d:
            self.decoder = str(d)
        e = opts.get("format.parquet.encoder")
        if e:
            self.encoder = str(e)
        self.dict_domain = resolve_dict_domain(opts.get("merge.dict-domain"))
        self.pool_limit = resolve_pool_limit(opts.get("merge.dict-domain.pool-limit"))
        return self

    def _effective_encoder(self, format_options: dict | None) -> str:
        # PAIMON_TPU_PARQUET_ENCODER lets scripts/verify.sh force the whole
        # suite through one encoder (same pattern as the pipeline stage's
        # PAIMON_TPU_SCAN_PARALLELISM)
        import os

        env = os.environ.get("PAIMON_TPU_PARQUET_ENCODER")
        if env:
            return env
        return str((format_options or {}).get("format.parquet.encoder") or self.encoder)

    def write(
        self,
        file_io: FileIO,
        path: str,
        batch: ColumnBatch,
        compression: str = "zstd",
        format_options: dict | None = None,
    ) -> None:
        import io as _io

        import pyarrow.parquet as pq

        if self._effective_encoder(format_options) == "native":
            from ..decode.container import UnsupportedParquetFeature
            from ..encode import write_native

            try:
                write_native(file_io, path, batch, compression, format_options)
                return
            except UnsupportedParquetFeature:
                # per-FILE fallback: this batch needs a feature outside the
                # native envelope (nested columns, exotic codec); later
                # files still try the native path
                from ..metrics import encode_metrics

                encode_metrics().counter("files_fallback").inc()

        table = batch.to_arrow()
        buf = _io.BytesIO()
        opts = format_options or {}
        kw = {}
        if "parquet.row-group.rows" in opts:
            kw["row_group_size"] = int(opts["parquet.row-group.rows"])
        elif "file.block-size" in opts and table.num_rows:
            # block-size is bytes; pyarrow sizes row groups in rows —
            # translate through the actual in-memory bytes/row of this table
            per_row = max(1, table.nbytes // table.num_rows)
            kw["row_group_size"] = max(1024, int(opts["file.block-size"]) // per_row)
        if "parquet.enable.dictionary" in opts:
            kw["use_dictionary"] = str(opts["parquet.enable.dictionary"]).lower() == "true"
        if "parquet.page-size" in opts:
            # smaller pages = finer native-decoder pushdown granularity
            kw["data_page_size"] = int(opts["parquet.page-size"])
        if "parquet.data-page-version" in opts:
            kw["data_page_version"] = str(opts["parquet.data-page-version"])
        if compression == "zstd" and "file.compression.zstd-level" in opts:
            kw["compression_level"] = int(opts["file.compression.zstd-level"])
        pq.write_table(table, buf, compression=compression, **kw)
        file_io.write_bytes(path, buf.getvalue())

    def read(
        self,
        file_io: FileIO,
        path: str,
        schema: RowType,
        projection: Sequence[str] | None = None,
        predicate: Predicate | None = None,
    ) -> Iterator[ColumnBatch]:
        import pyarrow.parquet as pq

        cols = list(projection) if projection is not None else schema.field_names
        read_schema = schema.project(cols)
        if self.decoder == "native":
            batches = self._read_native(file_io, path, schema, cols, predicate)
            if batches is not None:
                # fully materialized before the first yield, so an
                # unsupported feature can still fall back without
                # double-emitting rows
                for b in batches:
                    if b.num_rows:
                        yield b
                return
        # prefer a real OS path: pyarrow then memory-maps and reads through
        # its own C++ IO instead of a Python-file shim (which is both slower
        # and flaky under concurrent threaded decode — see FileIO.local_path)
        lp = file_io.local_path(path)
        f = lp if lp is not None else file_io.open_input(path)
        pf = None
        kw = {}
        if self.dict_domain:
            # merge.dict-domain through the ARROW decoder: ask arrow to keep
            # string/bytes columns dictionary-encoded — from_arrow then
            # populates the code domain in one C pass per chunk, so the
            # compressed merge fires regardless of decoder choice
            kw["read_dictionary"] = [
                n for n in cols if read_schema.field(n).type.root in _STRING_ROOTS
            ]
        try:
            try:
                pf = pq.ParquetFile(f, memory_map=True, **kw)
            except (KeyError, OSError, ValueError):
                if not kw:
                    raise
                # a requested dictionary column isn't a plain leaf in this
                # file (e.g. a collect aggregate stored the STRING field as
                # a list) — read it expanded like before
                kw = {}
                pf = pq.ParquetFile(f, memory_map=True)
            md = pf.metadata
            name_to_idx = {md.schema.column(i).name: i for i in range(md.num_columns)}
            keep = [
                rg
                for rg in range(md.num_row_groups)
                if predicate is None
                or predicate.test_stats(
                    _row_group_stats(md, rg, name_to_idx, predicate.referenced_fields(), schema)
                )
            ]
            # batch consecutive groups into one read call (pyarrow decodes
            # columns and groups in parallel internally, where a
            # group-at-a-time loop is single-threaded per step) — but bound
            # each call's uncompressed bytes so a multi-GB file still
            # streams instead of materializing whole
            budget = 256 << 20
            i = 0
            while i < len(keep):
                chunk = [keep[i]]
                spent = md.row_group(keep[i]).total_byte_size
                i += 1
                while i < len(keep) and spent + md.row_group(keep[i]).total_byte_size <= budget:
                    spent += md.row_group(keep[i]).total_byte_size
                    chunk.append(keep[i])
                    i += 1
                table = pf.read_row_groups(chunk, columns=cols)
                if table.num_rows:
                    yield ColumnBatch.from_arrow(table, read_schema)
        finally:
            if lp is None:
                f.close()
            elif pf is not None:
                pf.close()

    def _read_native(self, file_io, path, schema, cols, predicate):
        """Native decode of one file, or None to fall back to arrow."""
        from ..decode import UnsupportedParquetFeature, read_native

        try:
            return read_native(
                file_io,
                path,
                schema,
                projection=cols,
                predicate=predicate,
                dict_domain=self.dict_domain,
                pool_limit=self.pool_limit,
            )
        except UnsupportedParquetFeature:
            from ..metrics import decode_metrics

            decode_metrics().counter("files_fallback").inc()
            return None


def _row_group_stats(
    md, rg: int, name_to_idx: dict, fields: set[str], schema: RowType
) -> dict[str, FieldStats]:
    out: dict[str, FieldStats] = {}
    group = md.row_group(rg)
    for name in fields:
        idx = name_to_idx.get(name)
        if idx is None or name not in schema:
            continue
        col = group.column(idx)
        st = col.statistics
        if st is None or not st.has_min_max:
            continue
        # unknown null count must not prune null predicates
        nulls = st.null_count if st.has_null_count else None
        dtype = schema.field(name).type
        out[name] = FieldStats(
            _normalize_stat(st.min, dtype), _normalize_stat(st.max, dtype), nulls, group.num_rows
        )
    return out


def _normalize_stat(v, dtype):
    """Map arrow-logical stat values (datetime/date/Decimal) onto the internal
    physical representation that predicate literals use (micros / days /
    unscaled int64), mirroring ColumnBatch.from_arrow's normalization."""
    import datetime
    import decimal

    if v is None:
        return None
    if isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1, tzinfo=v.tzinfo)
        return int((v - epoch).total_seconds() * 1_000_000)
    if isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(v, decimal.Decimal):
        scale = dtype.scale or 0
        return int(v.scaleb(scale))
    return v


register_format("parquet", ParquetFormat)
