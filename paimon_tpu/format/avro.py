"""Avro data format: a self-contained binary codec (no external library).

Parity: /root/reference/paimon-format/.../avro/ — row-oriented Avro
read/write (the reference also uses Avro for manifests). Implements the Avro
1.x object container format: magic 'Obj\\x01', metadata map (schema JSON +
codec), 16-byte sync marker, blocks of (count, size, payload) with
null/deflate codecs; records as zigzag-varint primitives with ["null", T]
unions for nullable fields.

Row-oriented by nature — used for parity and for workloads that read whole
rows; columnar scans prefer parquet/orc.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Iterator, Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch
from ..data.predicate import Predicate
from ..fs import FileIO
from ..types import DataType, RowType, TypeRoot
from . import FileFormat, register_format

_MAGIC = b"Obj\x01"


# ---- varint / zigzag -----------------------------------------------------

def _write_long(out: bytearray, v: int) -> None:
    v = (v << 1) ^ (v >> 63)  # zigzag
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_long(buf: memoryview, pos: int) -> tuple[int, int]:
    shift = 0
    acc = 0
    while True:
        b = buf[pos]
        pos += 1
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1), pos


# ---- schema mapping ------------------------------------------------------

_AVRO_TYPES = {
    TypeRoot.BOOLEAN: "boolean",
    TypeRoot.TINYINT: "int",
    TypeRoot.SMALLINT: "int",
    TypeRoot.INT: "int",
    TypeRoot.DATE: "int",
    TypeRoot.TIME: "int",
    TypeRoot.BIGINT: "long",
    TypeRoot.TIMESTAMP: "long",
    TypeRoot.TIMESTAMP_LTZ: "long",
    TypeRoot.DECIMAL: "long",
    TypeRoot.FLOAT: "float",
    TypeRoot.DOUBLE: "double",
    TypeRoot.CHAR: "string",
    TypeRoot.VARCHAR: "string",
    TypeRoot.BINARY: "bytes",
    TypeRoot.VARBINARY: "bytes",
}


def _avro_schema(schema: RowType) -> dict:
    fields = []
    for f in schema.fields:
        t = _AVRO_TYPES.get(f.type.root)
        if t is None:
            raise ValueError(f"avro format does not support {f.type.root}")
        fields.append({"name": f.name, "type": ["null", t] if f.type.nullable else t})
    return {"type": "record", "name": "record", "fields": fields}


class AvroFormat(FileFormat):
    identifier = "avro"

    def write(self, file_io: FileIO, path: str, batch: ColumnBatch, compression: str = "deflate", format_options: dict | None = None) -> None:
        schema = batch.schema
        meta = {
            "avro.schema": json.dumps(_avro_schema(schema)).encode(),
            "avro.codec": b"deflate" if compression in ("deflate", "zstd", "zlib") else b"null",
        }
        sync = os.urandom(16)
        out = bytearray()
        out += _MAGIC
        _write_long(out, len(meta))
        for k, v in meta.items():
            kb = k.encode()
            _write_long(out, len(kb))
            out += kb
            _write_long(out, len(v))
            out += v
        _write_long(out, 0)  # end of metadata map
        out += sync
        try:
            block = self._encode_block_native(batch)
        except Exception:
            block = None  # anything the fast path can't express
        if block is None:
            block = self._encode_block(batch)
        if meta["avro.codec"] == b"deflate":
            block = zlib.compress(block)[2:-4]  # raw deflate per avro spec
        _write_long(out, batch.num_rows)
        _write_long(out, len(block))
        out += block
        out += sync
        file_io.write_bytes(path, bytes(out))

    @staticmethod
    def _encode_block_native(batch: ColumnBatch) -> bytes | None:
        """C encoder fast path: numeric columns pass through as arrays,
        strings as arrow offsets/data buffers (built by arrow's C++)."""
        from ..native import (
            CODE_BOOL,
            CODE_DOUBLE,
            CODE_FLOAT,
            CODE_LONG,
            CODE_STRING,
            avro_encoder,
        )

        import pyarrow as pa

        code_of = {
            TypeRoot.TINYINT: CODE_LONG, TypeRoot.SMALLINT: CODE_LONG, TypeRoot.INT: CODE_LONG,
            TypeRoot.BIGINT: CODE_LONG, TypeRoot.DATE: CODE_LONG, TypeRoot.TIME: CODE_LONG,
            TypeRoot.TIMESTAMP: CODE_LONG, TypeRoot.TIMESTAMP_LTZ: CODE_LONG, TypeRoot.DECIMAL: CODE_LONG,
            TypeRoot.FLOAT: CODE_FLOAT, TypeRoot.DOUBLE: CODE_DOUBLE, TypeRoot.BOOLEAN: CODE_BOOL,
            TypeRoot.CHAR: CODE_STRING, TypeRoot.VARCHAR: CODE_STRING,
            TypeRoot.BINARY: CODE_STRING, TypeRoot.VARBINARY: CODE_STRING,
        }
        specs = []
        cols = []
        for f in batch.schema.fields:
            code = code_of.get(f.type.root)
            if code is None:
                return None
            specs.append((code, f.type.nullable))
            col = batch.column(f.name)
            validity = col.validity
            if code == CODE_STRING:
                arr = (
                    col.arrow
                    if col._values is None and col.arrow is not None
                    else pa.array(col.values, from_pandas=True)
                )
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
                target = pa.binary() if f.type.root in (TypeRoot.BINARY, TypeRoot.VARBINARY) else pa.utf8()
                if arr.type != target:
                    arr = arr.cast(target)
                if arr.offset != 0:
                    arr = pa.concat_arrays([arr])  # rebase to offset 0
                bufs = arr.buffers()
                offsets = np.frombuffer(bufs[1], dtype=np.int32, count=len(arr) + 1)
                data = (
                    np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None else np.empty(0, np.uint8)
                )
                if validity is None and arr.null_count:
                    import pyarrow.compute as pc

                    validity = np.asarray(pc.is_valid(arr))
                cols.append((offsets, data, validity))
            else:
                cols.append((col.values, validity))
        return avro_encoder(batch.num_rows, specs, cols)

    @staticmethod
    def _encode_block(batch: ColumnBatch) -> bytes:
        out = bytearray()
        cols = [(batch.column(f.name), f.type) for f in batch.schema.fields]
        pylists = [(c.to_pylist(), t) for c, t in cols]
        for i in range(batch.num_rows):
            for values, t in pylists:
                v = values[i]
                nullable = t.nullable
                if nullable:
                    if v is None:
                        _write_long(out, 0)
                        continue
                    _write_long(out, 1)
                root = t.root
                if root == TypeRoot.BOOLEAN:
                    out.append(1 if v else 0)
                elif root in (TypeRoot.FLOAT,):
                    out += struct.pack("<f", v)
                elif root in (TypeRoot.DOUBLE,):
                    out += struct.pack("<d", v)
                elif root in (TypeRoot.CHAR, TypeRoot.VARCHAR):
                    b = str(v).encode()
                    _write_long(out, len(b))
                    out += b
                elif root in (TypeRoot.BINARY, TypeRoot.VARBINARY):
                    b = bytes(v)
                    _write_long(out, len(b))
                    out += b
                else:
                    _write_long(out, int(v))
        return bytes(out)

    def read(
        self,
        file_io: FileIO,
        path: str,
        schema: RowType,
        projection: Sequence[str] | None = None,
        predicate: Predicate | None = None,
    ) -> Iterator[ColumnBatch]:
        data = file_io.read_bytes(path)
        assert data[:4] == _MAGIC, "not an avro object container"
        buf = memoryview(data)
        pos = 4
        meta: dict[str, bytes] = {}
        while True:
            count, pos = _read_long(buf, pos)
            if count == 0:
                break
            for _ in range(abs(count)):
                klen, pos = _read_long(buf, pos)
                k = bytes(buf[pos : pos + klen]).decode()
                pos += klen
                vlen, pos = _read_long(buf, pos)
                meta[k] = bytes(buf[pos : pos + vlen])
                pos += vlen
        codec = meta.get("avro.codec", b"null")
        file_schema = json.loads(meta["avro.schema"].decode())
        pos += 16  # sync
        field_types = self._field_types(file_schema)
        names = [f["name"] for f in file_schema["fields"]]
        out_names = list(projection) if projection is not None else [n for n in schema.field_names if n in names]
        read_schema = schema.project(out_names)
        block_cols: list[dict[str, Column]] = []
        while pos < len(buf):
            count, pos = _read_long(buf, pos)
            size, pos = _read_long(buf, pos)
            payload = bytes(buf[pos : pos + size])
            pos += size + 16  # skip sync
            if codec == b"deflate":
                payload = zlib.decompress(payload, -15)
            decoded = self._decode_block_native(payload, count, field_types, names, read_schema)
            if decoded is None:
                # per-block python fallback (no compiler / input the C decoder
                # rejects) — converted to columns so paths merge in order
                rows = self._decode_block(payload, count, field_types)
                cols_data: dict[str, list] = {n: [] for n in names}
                for r in rows:
                    for n, v in zip(names, r):
                        cols_data[n].append(v)
                decoded = dict(
                    ColumnBatch.from_pydict(read_schema, {n: cols_data[n] for n in out_names}).columns
                )
            block_cols.append(decoded)
        if not block_cols:
            yield ColumnBatch.empty(read_schema)
            return
        merged = {
            n: Column.concat([blk[n] for blk in block_cols]) for n in out_names
        }
        yield ColumnBatch(read_schema, merged)

    @staticmethod
    def _decode_block_native(payload, count, field_types, names, read_schema):
        """C-decoder fast path: columnar buffers straight out of the block
        (paimon_tpu.native.avrodec); None -> caller uses the python loop."""
        from ..native import (
            CODE_BOOL,
            CODE_DOUBLE,
            CODE_FLOAT,
            CODE_LONG,
            CODE_STRING,
            avro_decoder,
        )

        code_of = {"int": CODE_LONG, "long": CODE_LONG, "float": CODE_FLOAT, "double": CODE_DOUBLE,
                   "boolean": CODE_BOOL, "string": CODE_STRING, "bytes": CODE_STRING}
        specs = []
        for nullable, t in field_types:
            code = code_of.get(t)
            if code is None:
                return None
            specs.append((code, nullable))
        out = avro_decoder(payload, count, specs)
        if out is None:
            return None
        import pyarrow as pa

        cols: dict[str, Column] = {}
        wanted = set(read_schema.field_names)
        for f, (name, (nullable, t)) in enumerate(zip(names, field_types)):
            if name not in wanted:
                continue
            res = out[f]
            target = read_schema.field(name).type
            if t in ("string", "bytes"):
                offsets, data, validity = res
                total = int(offsets[count])
                arr_type = pa.binary() if t == "bytes" else pa.utf8()
                vbuf = None
                valid = validity.astype(np.bool_)
                if not valid.all():
                    vbuf = pa.py_buffer(np.packbits(valid, bitorder="little").tobytes())
                arr = pa.Array.from_buffers(
                    arr_type,
                    count,
                    [vbuf, pa.py_buffer(offsets[: count + 1].tobytes()), pa.py_buffer(data[:total].tobytes())],
                )
                cols[name] = Column(validity=None if valid.all() else valid, arrow=arr)
            else:
                values, validity = res
                valid = validity.astype(np.bool_)
                np_dtype = target.numpy_dtype()
                if values.dtype != np_dtype:
                    values = values.astype(np_dtype)
                cols[name] = Column(values, None if valid.all() else valid)
        return cols

    @staticmethod
    def _field_types(file_schema: dict) -> list[tuple[bool, str]]:
        out = []
        for f in file_schema["fields"]:
            t = f["type"]
            if isinstance(t, list):
                base = [x for x in t if x != "null"][0]
                out.append((True, base))
            else:
                out.append((False, t))
        return out

    @staticmethod
    def _decode_block(payload: bytes, count: int, field_types: list[tuple[bool, str]]) -> list[list]:
        buf = memoryview(payload)
        pos = 0
        rows = []
        for _ in range(count):
            row = []
            for nullable, t in field_types:
                if nullable:
                    branch, pos = _read_long(buf, pos)
                    if branch == 0:
                        row.append(None)
                        continue
                if t == "boolean":
                    row.append(buf[pos] == 1)
                    pos += 1
                elif t == "float":
                    row.append(struct.unpack_from("<f", buf, pos)[0])
                    pos += 4
                elif t == "double":
                    row.append(struct.unpack_from("<d", buf, pos)[0])
                    pos += 8
                elif t == "string":
                    n, pos = _read_long(buf, pos)
                    row.append(bytes(buf[pos : pos + n]).decode())
                    pos += n
                elif t == "bytes":
                    n, pos = _read_long(buf, pos)
                    row.append(bytes(buf[pos : pos + n]))
                    pos += n
                else:  # int / long
                    v, pos = _read_long(buf, pos)
                    row.append(v)
            rows.append(row)
        return rows


register_format("avro", AvroFormat)
