"""L2: columnar file formats + per-file stats + secondary file indexes.

Capability parity with the reference format SPI
(/root/reference/paimon-common/.../format/FileFormat.java:41 — discovery via
identifier, createReaderFactory/createWriterFactory :59-63; impls in
paimon-format/: parquet, orc, avro) and SimpleStatsCollector/Extractor.

TPU-first decisions:
  * container parsing (parquet/orc structure, compression) stays on host via
    pyarrow's C++ readers — that path is already vectorized and feeds numpy
    buffers that transfer to device untouched;
  * per-file, per-field min/max/null-count stats are collected vectorized at
    write time and embedded in DataFileMeta for planner pruning;
  * predicate pushdown happens twice: row-group/stripe skipping inside the
    reader (host) and dense mask eval on the decoded batch (device-capable).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, Sequence

import numpy as np

from ..data.batch import ColumnBatch
from ..data.predicate import FieldStats, Predicate
from ..fs import FileIO
from ..types import RowType, TypeRoot

__all__ = [
    "FileFormat",
    "get_format",
    "register_format",
    "collect_stats",
    "stats_to_json",
    "stats_from_json",
]


class FileFormat:
    """A data file format: writes a ColumnBatch to one file, reads it back
    (with projection + predicate pushdown)."""

    identifier: str = "?"

    def configure(self, format_options: dict | None) -> "FileFormat":
        """Apply reader-side format options (e.g. format.parquet.decoder)
        to this instance; default is a no-op. Returns self for chaining."""
        return self

    def write(
        self,
        file_io: FileIO,
        path: str,
        batch: ColumnBatch,
        compression: str = "zstd",
        format_options: dict | None = None,
    ) -> None:
        raise NotImplementedError

    def read(
        self,
        file_io: FileIO,
        path: str,
        schema: RowType,
        projection: Sequence[str] | None = None,
        predicate: Predicate | None = None,
    ) -> Iterator[ColumnBatch]:
        raise NotImplementedError


_FORMATS: dict[str, Callable[[], FileFormat]] = {}


def register_format(identifier: str, factory: Callable[[], FileFormat]) -> None:
    _FORMATS[identifier] = factory


def get_format(identifier: str) -> FileFormat:
    if identifier not in _FORMATS:
        # lazy import of built-ins
        from . import avro, orc, parquet  # noqa: F401

    if identifier not in _FORMATS:
        raise ValueError(f"unknown file format {identifier!r}; known: {sorted(_FORMATS)}")
    return _FORMATS[identifier]()


# ---- stats ---------------------------------------------------------------

_TRUNCATE_LEN = 16


def collect_stats(batch: ColumnBatch, truncate: int = _TRUNCATE_LEN) -> dict[str, FieldStats]:
    """Vectorized per-field min/max/null-count (reference SimpleStatsCollector).
    String min/max are truncated to `truncate` chars (metadata.stats-mode
    truncate(16)): truncation keeps min a lower bound; the truncated max is
    bumped so it stays an upper bound."""
    out: dict[str, FieldStats] = {}
    n = batch.num_rows
    for f in batch.schema.fields:
        col = batch.column(f.name)
        nulls = col.null_count
        if nulls >= n or n == 0:
            out[f.name] = FieldStats(None, None, nulls, n)
            continue
        if f.type.root in (TypeRoot.ARRAY, TypeRoot.MAP, TypeRoot.ROW):
            # nested values have no total order: null-count-only stats
            out[f.name] = FieldStats(None, None, nulls, n)
            continue
        if f.type.numpy_dtype() == np.dtype(object):
            cache = getattr(col, "dict_cache", None)
            if cache is not None and len(cache[1]) == n:
                # key-lane pool reuse: the pool is sorted, so min/max are a
                # uint32 reduction over the (valid) ranks — no object
                # comparisons, and a code-backed column never expands
                pool, codes = cache
                if nulls:
                    codes = codes[col.validity]
                lo, hi = pool[int(codes.min())], pool[int(codes.max())]
            else:
                v = col.values[col.valid_mask()] if nulls else col.values
                lo, hi = min(v), max(v)
            lo, hi = _truncate_min(lo, truncate), _truncate_max(hi, truncate)
            out[f.name] = FieldStats(lo, hi, nulls, n)
            continue
        v = col.values[col.valid_mask()] if nulls else col.values
        if v.dtype.kind == "f":
            # NaN-ignoring reductions: a NaN min/max would defeat every
            # stats comparison and prune files that contain matches
            with np.errstate(invalid="ignore"):
                lo, hi = np.nanmin(v), np.nanmax(v)
            if np.isnan(lo) or np.isnan(hi):
                out[f.name] = FieldStats(None, None, nulls, n)
                continue
            lo, hi = _to_py(lo), _to_py(hi)
        else:
            lo, hi = _to_py(v.min()), _to_py(v.max())
        out[f.name] = FieldStats(lo, hi, nulls, n)
    return out


def _to_py(x):
    return x.item() if hasattr(x, "item") else x


def _truncate_min(x, limit: int):
    if isinstance(x, (str, bytes)) and len(x) > limit:
        return x[:limit]
    return x


def _truncate_max(x, limit: int):
    if isinstance(x, str) and len(x) > limit:
        t = x[:limit]
        # bump last char so truncated value stays >= every original
        for i in range(len(t) - 1, -1, -1):
            if ord(t[i]) < 0x10FFFF:
                return t[:i] + chr(ord(t[i]) + 1)
        return x
    if isinstance(x, bytes) and len(x) > limit:
        t = bytearray(x[:limit])
        for i in range(len(t) - 1, -1, -1):
            if t[i] < 0xFF:
                t[i] += 1
                return bytes(t[: i + 1])
        return x
    return x


def stats_to_json(stats: dict[str, FieldStats]) -> dict:
    def enc(v):
        if isinstance(v, bytes):
            return {"b64": __import__("base64").b64encode(v).decode()}
        if isinstance(v, (bool, int, float, str)) or v is None:
            return v
        return str(v)

    return {
        name: {"min": enc(s.min), "max": enc(s.max), "nullCount": s.null_count, "rowCount": s.row_count}
        for name, s in stats.items()
    }


def stats_from_json(d: dict) -> dict[str, FieldStats]:
    def dec(v):
        if isinstance(v, dict) and "b64" in v:
            return __import__("base64").b64decode(v["b64"])
        return v

    return {
        name: FieldStats(dec(s["min"]), dec(s["max"]), s["nullCount"], s["rowCount"])
        for name, s in d.items()
    }
