"""ORC read/write over pyarrow.

Parity: /root/reference/paimon-format/.../orc/OrcReaderFactory.java (batch
decode into column vectors, SearchArgument pushdown). pyarrow exposes stripes
but not stripe statistics, so pruning happens at file level (DataFileMeta
stats) and via dense mask eval after decode; stripe iteration keeps memory
bounded for large files.
"""

from __future__ import annotations

from typing import Iterator, Sequence

from ..data.batch import ColumnBatch
from ..data.predicate import Predicate
from ..fs import FileIO
from ..types import RowType
from . import FileFormat, register_format


class OrcFormat(FileFormat):
    identifier = "orc"

    def write(self, file_io: FileIO, path: str, batch: ColumnBatch, compression: str = "zstd") -> None:
        import io as _io

        import pyarrow.orc as po

        table = batch.to_arrow()
        buf = _io.BytesIO()
        po.write_table(table, buf, compression=compression)
        file_io.write_bytes(path, buf.getvalue())

    def read(
        self,
        file_io: FileIO,
        path: str,
        schema: RowType,
        projection: Sequence[str] | None = None,
        predicate: Predicate | None = None,
    ) -> Iterator[ColumnBatch]:
        import pyarrow.orc as po

        cols = list(projection) if projection is not None else schema.field_names
        read_schema = schema.project(cols)
        f = file_io.open_input(path)
        try:
            of = po.ORCFile(f)
            for stripe in range(of.nstripes):
                table = of.read_stripe(stripe, columns=cols)
                if isinstance(table, __import__("pyarrow").RecordBatch):
                    table = __import__("pyarrow").Table.from_batches([table])
                if table.num_rows:
                    yield ColumnBatch.from_arrow(table, read_schema)
        finally:
            f.close()


register_format("orc", OrcFormat)
