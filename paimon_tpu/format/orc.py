"""ORC read/write over pyarrow, with stripe-statistics pruning.

Parity: /root/reference/paimon-format/.../orc/OrcReaderFactory.java (batch
decode into column vectors, SearchArgument pushdown into the ORC reader).
pyarrow decodes stripes but exposes no stripe statistics, so orc_meta.py
reads them straight from the file tail; Predicate.test_stats then skips
whole stripes before any decode — the same evaluator used for file- and
parquet-row-group-level pruning.
"""

from __future__ import annotations

from typing import Iterator, Sequence

from ..data.batch import ColumnBatch
from ..data.predicate import Predicate
from ..fs import FileIO
from ..types import RowType
from . import FileFormat, register_format


class OrcFormat(FileFormat):
    identifier = "orc"

    def write(
        self,
        file_io: FileIO,
        path: str,
        batch: ColumnBatch,
        compression: str = "zstd",
        format_options: dict | None = None,
    ) -> None:
        import io as _io

        import pyarrow.orc as po

        table = batch.to_arrow()
        buf = _io.BytesIO()
        opts = format_options or {}
        stripe_size = int(opts.get("orc.stripe.size", opts.get("file.block-size", 64 << 20)))
        po.write_table(table, buf, compression=compression, stripe_size=stripe_size)
        file_io.write_bytes(path, buf.getvalue())

    def read(
        self,
        file_io: FileIO,
        path: str,
        schema: RowType,
        projection: Sequence[str] | None = None,
        predicate: Predicate | None = None,
    ) -> Iterator[ColumnBatch]:
        import pyarrow as pa
        import pyarrow.orc as po

        cols = list(projection) if projection is not None else schema.field_names
        read_schema = schema.project(cols)
        # real OS path -> pyarrow's own C++ IO (no Python-file shim; see
        # FileIO.local_path); stream path only for non-local/intercepted IO
        lp = file_io.local_path(path)
        f = open(lp, "rb") if lp is not None else file_io.open_input(path)
        try:
            tail = None
            if predicate is not None:
                from ..metrics import registry

                try:
                    tail = _read_tail_from(f)
                except Exception:  # malformed/foreign tail: read everything
                    tail = None
                f.seek(0)
            of = po.ORCFile(lp if lp is not None else f)
            for stripe in range(of.nstripes):
                if tail is not None and stripe < tail.nstripes:
                    if not predicate.test_stats(tail.stripe_stats(stripe)):
                        registry.group("scan").counter("orc_stripes_skipped").inc()
                        continue
                table = of.read_stripe(stripe, columns=cols)
                if isinstance(table, pa.RecordBatch):
                    table = pa.Table.from_batches([table])
                if table.num_rows:
                    yield ColumnBatch.from_arrow(table, read_schema)
        finally:
            f.close()


def _read_tail_from(f, first_guess: int = 256 * 1024):
    """Parse the OrcTail from just the trailing region holding
    postscript+footer+metadata — decode stays stripe-by-stripe on the file
    handle, memory stays bounded, and the tail parses exactly once."""
    from .orc_meta import read_tail

    size = f.seek(0, 2)
    take = min(size, first_guess)
    f.seek(size - take)
    data = f.read(take)
    try:
        return read_tail(data)
    except ValueError:  # tail larger than the guess: take the whole file
        f.seek(0)
        return read_tail(f.read())


register_format("orc", OrcFormat)
