"""Per-file secondary index: bloom filters in a sidecar container.

Parity: /root/reference/paimon-common/.../fileindex/ — FileIndexer SPI,
FileIndexFormat container (FileIndexFormat.java:99), bloomfilter/
BloomFilterFileIndex.java; FileIndexPredicate evaluates predicates against the
index to skip whole files. Hashing and membership tests are vectorized numpy
(batched across all probe values at once), not per-row loops.

Container layout (one `.index` sidecar per data file):
  [4 bytes magic "PTIX"] [4 bytes header length] [JSON header] [bitmap blobs]
  header = {"columns": {name: {"type": "bloom", "offset": o, "length": l,
                                "numHashFunctions": k, "numBits": m}}}
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import Iterable, Sequence

import numpy as np

from ..data.batch import Column, ColumnBatch
from ..data.predicate import CompoundPredicate, LeafPredicate, Predicate
from ..fs import FileIO

__all__ = [
    "BloomFilter",
    "write_file_index",
    "FileIndexPredicate",
    "index_path",
    "KEY_INDEX_NAME",
    "resolve_key_bloom",
]

_MAGIC = b"PTIX"

# the composite primary-key bloom rides in the PTIX container as a pseudo
# column (reference: bloom-filter file index per column; the key entry is the
# point-get extension — one bloom over the combined key-column hash, the same
# splitmix64 hash the bucket router and lookup files use)
KEY_INDEX_NAME = "__KEY__"


def resolve_key_bloom(enabled: bool | str | None) -> bool:
    """One resolution order everywhere (mirrors ops.dicts.resolve_dict_domain):
    the PAIMON_TPU_KEY_BLOOM env var (the verify `get` stage forces both
    paths) beats the caller's option value, which beats the default (off)."""
    import os

    env = os.environ.get("PAIMON_TPU_KEY_BLOOM", "").strip().lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    if enabled is None:
        return False
    if isinstance(enabled, str):
        return enabled.strip().lower() in ("1", "on", "true")
    return bool(enabled)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
    return x ^ (x >> np.uint64(31))


def _hash64(values: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit hashes for a column (vectorized for numerics)."""
    if values.dtype == np.dtype(object):
        out = np.empty(len(values), dtype=np.uint64)
        for i, v in enumerate(values):
            b = v.encode("utf-8") if isinstance(v, str) else (v if isinstance(v, bytes) else str(v).encode())
            out[i] = (zlib.crc32(b) | (np.uint64(zlib.adler32(b)) << np.uint64(32))) & np.uint64(0xFFFFFFFFFFFFFFFF)
        return _splitmix64(out)
    if values.dtype.kind == "f":
        # normalize -0.0 == 0.0 before bit reinterpretation
        values = values + 0.0
        values = values.astype(np.float64).view(np.uint64)
    else:
        values = values.astype(np.int64).view(np.uint64)
    return _splitmix64(values)


def _hash_scalar(v) -> np.uint64:
    if isinstance(v, (str, bytes)):
        arr = np.empty(1, dtype=object)
        arr[0] = v
        return _hash64(arr)[0]
    if isinstance(v, float):
        return _hash64(np.array([v], dtype=np.float64))[0]
    if isinstance(v, bool):
        return _hash64(np.array([int(v)], dtype=np.int64))[0]
    return _hash64(np.array([v], dtype=np.int64))[0]


class BloomFilter:
    """Standard k-hash bloom over double hashing h1 + i*h2 (vectorized)."""

    def __init__(self, num_bits: int, num_hashes: int, bits: np.ndarray | None = None):
        self.num_bits = num_bits
        self.num_hashes = num_hashes
        nwords = (num_bits + 63) // 64
        self.words = bits if bits is not None else np.zeros(nwords, dtype=np.uint64)

    @staticmethod
    def for_items(n: int, fpp: float) -> "BloomFilter":
        n = max(n, 1)
        m = max(1024, int(-n * math.log(fpp) / (math.log(2) ** 2)))
        k = max(1, min(20, round(-math.log(fpp) / math.log(2))))
        return BloomFilter(m, k)

    def _positions(self, hashes: np.ndarray) -> np.ndarray:
        h1 = hashes & np.uint64(0xFFFFFFFF)
        h2 = hashes >> np.uint64(32)
        i = np.arange(self.num_hashes, dtype=np.uint64)
        combined = h1[:, None] + i[None, :] * h2[:, None]
        return (combined % np.uint64(self.num_bits)).astype(np.uint64)

    def add_hashes(self, hashes: np.ndarray) -> None:
        pos = self._positions(hashes).ravel()
        np.bitwise_or.at(self.words, (pos >> np.uint64(6)).astype(np.int64), np.uint64(1) << (pos & np.uint64(63)))

    def might_contain_hashes(self, hashes: np.ndarray) -> np.ndarray:
        pos = self._positions(hashes)
        word = self.words[(pos >> np.uint64(6)).astype(np.int64)]
        bit = (word >> (pos & np.uint64(63))) & np.uint64(1)
        return bit.all(axis=1)

    def might_contain(self, value) -> bool:
        return bool(self.might_contain_hashes(np.array([_hash_scalar(value)], dtype=np.uint64))[0])

    def to_bytes(self) -> bytes:
        return self.words.tobytes()

    @staticmethod
    def from_bytes(data: bytes, num_bits: int, num_hashes: int) -> "BloomFilter":
        return BloomFilter(num_bits, num_hashes, np.frombuffer(data, dtype=np.uint64).copy())


def index_path(data_file_path: str) -> str:
    return data_file_path + ".index"


def build_index_payload(
    batch: ColumnBatch,
    columns: Sequence[str],
    fpp: float = 0.05,
    key_hashes: np.ndarray | None = None,
    key_fpp: float = 0.001,
) -> bytes | None:
    """The PTIX container bytes for `columns`, or None when nothing to index.
    Callers decide placement: sidecar file, or embedded in the manifest entry
    when small (reference file-index.in-manifest-threshold).

    `key_hashes`: optional (n,) uint64 combined primary-key hashes
    (table.bucket.key_hashes) — adds the composite KEY_INDEX_NAME bloom the
    batched get path prunes files with. A tighter fpp than the per-column
    default: a point-get batch probes many keys per file, so the per-file
    false-positive budget must survive the union over the batch."""
    cols = [c for c in columns if c in batch.schema]
    if (not cols and key_hashes is None) or batch.num_rows == 0:
        return None
    header: dict = {"columns": {}}
    blobs: list[bytes] = []
    offset = 0

    def add(name: str, bf: BloomFilter, extra: dict | None = None) -> None:
        nonlocal offset
        blob = bf.to_bytes()
        header["columns"][name] = {
            "type": "bloom",
            "offset": offset,
            "length": len(blob),
            "numHashFunctions": bf.num_hashes,
            "numBits": bf.num_bits,
            **(extra or {}),
        }
        blobs.append(blob)
        offset += len(blob)

    for name in cols:
        col = batch.column(name)
        valid = col.valid_mask()
        values = col.values[valid]
        bf = BloomFilter.for_items(len(values), fpp)
        if len(values):
            bf.add_hashes(_hash64(values))
        add(name, bf)
    if key_hashes is not None and len(key_hashes):
        bf = BloomFilter.for_items(len(key_hashes), key_fpp)
        bf.add_hashes(np.asarray(key_hashes, dtype=np.uint64))
        add(KEY_INDEX_NAME, bf, {"key": True})
    hdr = json.dumps(header).encode()
    return _MAGIC + struct.pack("<I", len(hdr)) + hdr + b"".join(blobs)


def write_file_index(
    file_io: FileIO,
    data_file_path: str,
    batch: ColumnBatch,
    columns: Sequence[str],
    fpp: float = 0.05,
) -> str | None:
    """Build bloom indexes for `columns` of this file; returns sidecar path."""
    payload = build_index_payload(batch, columns, fpp)
    if payload is None:
        return None
    path = index_path(data_file_path)
    file_io.write_bytes(path, payload, overwrite=True)
    return path


class FileIndexPredicate:
    """Evaluates a predicate against a file's index sidecar: False => the file
    provably contains no matching row and is skipped."""

    def __init__(self, file_io: FileIO, idx_path: str):
        self._load(file_io.read_bytes(idx_path))

    def _load(self, data: bytes) -> None:
        assert data[:4] == _MAGIC, "bad index magic"
        (hlen,) = struct.unpack("<I", data[4:8])
        self.header = json.loads(data[8 : 8 + hlen])
        self.blob = data[8 + hlen :]

    @classmethod
    def from_bytes(cls, data: bytes) -> "FileIndexPredicate":
        self = cls.__new__(cls)
        self._load(data)
        return self

    def _bloom(self, name: str) -> BloomFilter | None:
        meta = self.header["columns"].get(name)
        if meta is None or meta["type"] != "bloom":
            return None
        raw = self.blob[meta["offset"] : meta["offset"] + meta["length"]]
        return BloomFilter.from_bytes(raw, meta["numBits"], meta["numHashFunctions"])

    def key_bloom(self) -> BloomFilter | None:
        """The composite primary-key bloom, or None for pre-key-index files."""
        return self._bloom(KEY_INDEX_NAME)

    def test_key_hashes(self, hashes: np.ndarray) -> np.ndarray | None:
        """(n,) bool mask — True where the key MIGHT be in this file — or
        None when the file carries no key index (cannot prune). One
        vectorized membership test for the whole probe batch."""
        bf = self.key_bloom()
        if bf is None:
            return None
        return bf.might_contain_hashes(np.asarray(hashes, dtype=np.uint64))

    def test(self, predicate: Predicate | None) -> bool:
        if predicate is None:
            return True
        return self._test(predicate)

    def _test(self, p: Predicate) -> bool:
        if isinstance(p, CompoundPredicate):
            if p.function == "and":
                return all(self._test(c) for c in p.children)
            return any(self._test(c) for c in p.children)
        assert isinstance(p, LeafPredicate)
        if p.function == "equal":
            bf = self._bloom(p.field)
            return True if bf is None else bf.might_contain(p.literals)
        if p.function == "in":
            bf = self._bloom(p.field)
            if bf is None:
                return True
            return any(bf.might_contain(v) for v in p.literals)
        return True  # only equality-like predicates can use blooms
