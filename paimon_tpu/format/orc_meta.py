"""ORC footer / stripe-statistics reader (no ORC library needed).

pyarrow decodes ORC stripes but exposes no stripe statistics, so round 1
shipped ORC without stripe pruning. This module reads them directly from the
file tail: PostScript -> Footer (types, per-stripe row counts) -> Metadata
(per-stripe column statistics), using a minimal protobuf wire-format reader
over the ~10 message shapes involved. Mirrors the pruning the reference gets
from the ORC library's SearchArgument pushdown
(/root/reference/paimon-format/.../orc/OrcReaderFactory.java,
OrcFilters SearchArgument construction).

Only the stats kinds predicates can use are materialized: integer, double,
string, boolean (true-count), date. Everything else yields no stats for the
column — pruning then stays conservative (stripe is read).
"""

from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass

from ..data.predicate import FieldStats

__all__ = ["OrcTail", "read_tail"]


# ---------------------------------------------------------------------------
# protobuf wire format
# ---------------------------------------------------------------------------


def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def fields_of(buf: bytes):
    """Yield (field_number, wire_type, value) over a protobuf message.
    value: int for varint/fixed, bytes for length-delimited."""
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:  # varint
            v, pos = _varint(buf, pos)
        elif wire == 1:  # fixed64
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        elif wire == 2:  # length-delimited
            ln, pos = _varint(buf, pos)
            v = buf[pos : pos + ln]
            pos += ln
        elif wire == 5:  # fixed32
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        else:  # pragma: no cover - groups unused by ORC
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


def _packed_varints(v) -> list[int]:
    if isinstance(v, int):
        return [v]
    out = []
    pos = 0
    while pos < len(v):
        x, pos = _varint(v, pos)
        out.append(x)
    return out


# ---------------------------------------------------------------------------
# ORC tail structures
# ---------------------------------------------------------------------------

_COMPRESSION = {0: "none", 1: "zlib", 2: "snappy", 3: "lzo", 4: "lz4", 5: "zstd"}

_KIND_STRUCT = 12  # orc_proto.Type.Kind.STRUCT


def _decompress_stream(raw: bytes, kind: str) -> bytes:
    """ORC compressed streams are chunked: 3-byte LE header
    (length << 1 | isOriginal) then chunk payload."""
    if kind == "none":
        return raw
    out = bytearray()
    pos = 0
    while pos + 3 <= len(raw):
        header = raw[pos] | (raw[pos + 1] << 8) | (raw[pos + 2] << 16)
        pos += 3
        length = header >> 1
        chunk = raw[pos : pos + length]
        pos += length
        if header & 1:  # original (stored uncompressed)
            out += chunk
        elif kind == "zlib":
            out += zlib.decompress(chunk, -15)  # raw deflate
        elif kind == "zstd":
            from ..utils.compression import zstd_decompress

            out += zstd_decompress(chunk)
        elif kind == "lz4":
            import pyarrow as pa

            out += pa.decompress(chunk, codec="lz4", asbytes=True)
        elif kind == "snappy":
            import pyarrow as pa

            out += pa.decompress(chunk, codec="snappy", asbytes=True)
        else:  # pragma: no cover
            raise ValueError(f"unsupported ORC compression {kind}")
    return bytes(out)


@dataclass
class _ColStats:
    values: int = 0
    has_null: bool = False
    min: object = None
    max: object = None
    true_count: int | None = None


def _parse_col_stats(buf: bytes) -> _ColStats:
    cs = _ColStats()
    for field, wire, v in fields_of(buf):
        if field == 1:
            cs.values = v
        elif field == 10:
            cs.has_null = bool(v)
        elif field == 2:  # IntegerStatistics (sint64 min/max)
            for f2, _, v2 in fields_of(v):
                if f2 == 1:
                    cs.min = _zigzag(v2)
                elif f2 == 2:
                    cs.max = _zigzag(v2)
        elif field == 3:  # DoubleStatistics (double min/max)
            for f2, w2, v2 in fields_of(v):
                if f2 in (1, 2):
                    x = struct.unpack("<d", struct.pack("<Q", v2))[0]
                    if f2 == 1:
                        cs.min = x
                    else:
                        cs.max = x
        elif field == 4:  # StringStatistics
            for f2, _, v2 in fields_of(v):
                if f2 == 1:
                    cs.min = v2.decode("utf-8", "replace")
                elif f2 == 2:
                    cs.max = v2.decode("utf-8", "replace")
        elif field == 5:  # BucketStatistics { repeated uint64 count [packed] }
            counts: list[int] = []
            for f2, _, v2 in fields_of(v):
                if f2 == 1:
                    counts.extend(_packed_varints(v2))
            if counts:
                cs.true_count = counts[0]
        elif field == 7:  # DateStatistics (sint32 days)
            for f2, _, v2 in fields_of(v):
                if f2 == 1:
                    cs.min = _zigzag(v2)
                elif f2 == 2:
                    cs.max = _zigzag(v2)
    return cs


@dataclass
class OrcTail:
    compression: str
    stripe_rows: list[int]  # rows per stripe (Footer.stripes)
    field_columns: dict[str, int]  # top-level field name -> flattened column id
    stripe_col_stats: list[list[_ColStats]]  # [stripe][column]

    @property
    def nstripes(self) -> int:
        return len(self.stripe_rows)

    def stripe_stats(self, stripe: int) -> dict[str, FieldStats]:
        """FieldStats per top-level field for one stripe — the same shape the
        scan layer feeds Predicate.test_stats, so file- and stripe-level
        pruning share one evaluator."""
        out: dict[str, FieldStats] = {}
        if stripe >= len(self.stripe_col_stats):
            return out
        cols = self.stripe_col_stats[stripe]
        rows = self.stripe_rows[stripe]
        for name, cid in self.field_columns.items():
            if cid >= len(cols):
                continue
            cs = cols[cid]
            null_count = rows - cs.values if cs.values <= rows else (0 if not cs.has_null else None)
            mn, mx = cs.min, cs.max
            if cs.true_count is not None:  # boolean column
                mn = cs.true_count >= cs.values  # min True iff NO False rows
                mx = cs.true_count > 0
            out[name] = FieldStats(mn, mx, null_count, rows)
        return out


def read_tail(data: bytes) -> OrcTail:
    """Parse the ORC tail from the file's final bytes (pass at least the last
    few KB; the whole file also works)."""
    ps_len = data[-1]
    ps = data[-1 - ps_len : -1]
    footer_len = metadata_len = 0
    compression = "none"
    for field, _, v in fields_of(ps):
        if field == 1:
            footer_len = v
        elif field == 2:
            compression = _COMPRESSION.get(v, "unknown")
        elif field == 5:
            metadata_len = v
    tail_needed = 1 + ps_len + footer_len + metadata_len
    if len(data) < tail_needed:
        raise ValueError("need more trailing bytes for ORC tail")
    footer_raw = data[-1 - ps_len - footer_len : -1 - ps_len]
    meta_raw = data[-1 - ps_len - footer_len - metadata_len : -1 - ps_len - footer_len]
    footer = _decompress_stream(footer_raw, compression)
    meta = _decompress_stream(meta_raw, compression)

    stripe_rows: list[int] = []
    types: list[tuple[int, list[int], list[str]]] = []  # kind, subtypes, field names
    for field, _, v in fields_of(footer):
        if field == 3:  # StripeInformation
            rows = 0
            for f2, _, v2 in fields_of(v):
                if f2 == 5:
                    rows = v2
            stripe_rows.append(rows)
        elif field == 4:  # Type
            kind = 0
            subtypes: list[int] = []
            names: list[str] = []
            for f2, w2, v2 in fields_of(v):
                if f2 == 1:
                    kind = v2
                elif f2 == 2:
                    subtypes.extend(_packed_varints(v2))
                elif f2 == 3:
                    names.append(v2.decode("utf-8"))
            types.append((kind, subtypes, names))

    field_columns: dict[str, int] = {}
    if types and types[0][0] == _KIND_STRUCT:
        _, subtypes, names = types[0]
        for name, cid in zip(names, subtypes):
            field_columns[name] = cid

    stripe_col_stats: list[list[_ColStats]] = []
    for field, _, v in fields_of(meta):
        if field == 1:  # StripeStatistics
            cols = [_parse_col_stats(v2) for f2, _, v2 in fields_of(v) if f2 == 1]
            stripe_col_stats.append(cols)

    return OrcTail(compression, stripe_rows, field_columns, stripe_col_stats)
