"""Config documentation generator.

Parity: /root/reference/paimon-docs/.../ConfigOptionsDocGenerator.java — the
reference auto-generates its option tables from the annotated ConfigOptions;
here the same table is derived by introspecting CoreOptions.

Usage: python -m paimon_tpu.docs_gen > docs/options.md
"""

from __future__ import annotations

import enum

from .options import ConfigOption, CoreOptions

__all__ = ["generate_options_doc"]


def _fmt_default(v) -> str:
    if v is None:
        return "(none)"
    if isinstance(v, enum.Enum):
        return v.value
    if isinstance(v, bool):
        return str(v).lower()
    return str(v)


def generate_options_doc() -> str:
    rows = []
    for name in dir(CoreOptions):
        opt = getattr(CoreOptions, name)
        if isinstance(opt, ConfigOption):
            rows.append((opt.key, _fmt_default(opt.default), opt.description))
    rows.sort()
    out = [
        "# Table options",
        "",
        "Auto-generated from `paimon_tpu.options.CoreOptions`",
        "(the analog of the reference's ConfigOptionsDocGenerator).",
        "",
        "| Key | Default | Description |",
        "|---|---|---|",
    ]
    for key, default, desc in rows:
        out.append(f"| `{key}` | {default} | {desc} |")
    out.append("")
    out.append(
        "Per-field options use the `fields.<name>.<suffix>` pattern: "
        "`aggregate-function`, `sequence-group`, `ignore-retract`, "
        "`list-agg-delimiter`, `distinct`."
    )
    return "\n".join(out) + "\n"


if __name__ == "__main__":
    print(generate_options_doc(), end="")
