"""Native (C) accelerators, built on demand and loaded via ctypes.

The reference has no native code (its hot loops ride the JVM JIT); here the
device kernels are the main "native" layer, but row-major container formats
like Avro cannot be columnarized before parsing — so their inner decode loop
is C. Compiled once per machine into ``_build/`` with ``cc -O3 -shared
-fPIC``; every caller falls back to the pure-python path when no compiler is
available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

__all__ = ["avro_decoder", "native_available"]

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()
_LIB: "ctypes.CDLL | None | bool" = None  # None = not tried, False = unavailable

# field type codes — must match avrodec.c
CODE_LONG = 0
CODE_FLOAT = 1
CODE_DOUBLE = 2
CODE_BOOL = 3
CODE_STRING = 4


def _load() -> "ctypes.CDLL | None":
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB or None
        so_path = os.path.join(_BUILD, "avrodec.so")
        src = os.path.join(_DIR, "avrodec.c")
        try:
            if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
                os.makedirs(_BUILD, exist_ok=True)
                # build to a private name, publish atomically: a concurrent
                # process must never dlopen a half-written library
                tmp_path = f"{so_path}.{os.getpid()}.tmp"
                subprocess.run(
                    ["cc", "-O3", "-shared", "-fPIC", "-o", tmp_path, src],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp_path, so_path)
            lib = ctypes.CDLL(so_path)
            lib.decode_block.restype = ctypes.c_int
            lib.encode_block.restype = ctypes.c_int64
            _LIB = lib
        except Exception:
            _LIB = False
        return _LIB or None


def native_available() -> bool:
    return _load() is not None


def avro_decoder(payload: bytes, count: int, field_specs: list[tuple[int, bool]]):
    """Decode one Avro block natively.

    field_specs: [(type_code, nullable)] per field. Returns a list of
    per-field results or None if the native library is unavailable:
      numeric/bool: (values ndarray, validity ndarray)
      string:       (offsets int32 ndarray (n+1), data bytes, validity)
    """
    lib = _load()
    if lib is None:
        return None
    nfields = len(field_specs)
    type_codes = np.array([c for c, _ in field_specs], dtype=np.int32)
    nullable = np.array([1 if n else 0 for _, n in field_specs], dtype=np.uint8)
    num_out = (ctypes.c_void_p * nfields)()
    valid_out = (ctypes.POINTER(ctypes.c_uint8) * nfields)()
    str_offsets = (ctypes.POINTER(ctypes.c_int32) * nfields)()
    str_data = (ctypes.POINTER(ctypes.c_uint8) * nfields)()
    str_cap = np.zeros(nfields, dtype=np.int64)

    results: list = [None] * nfields
    n_strings = sum(1 for c, _ in field_specs if c == CODE_STRING)
    # the fields' combined string bytes cannot exceed the payload, but any
    # ONE field may own almost all of it: start with an even share + slack
    # and retry once with the full payload size on overflow (rc == -2)
    cap_guess = max(64, len(payload) // max(n_strings, 1) + 1024)
    for attempt in range(2):
        for f, (code, _) in enumerate(field_specs):
            validity = np.empty(count, dtype=np.uint8)
            valid_out[f] = validity.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            if code == CODE_STRING:
                offsets = np.empty(count + 1, dtype=np.int32)
                data = np.empty(cap_guess, dtype=np.uint8)
                str_offsets[f] = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
                str_data[f] = data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
                str_cap[f] = cap_guess
                results[f] = (offsets, data, validity)
            else:
                dtype = {CODE_LONG: np.int64, CODE_FLOAT: np.float64, CODE_DOUBLE: np.float64, CODE_BOOL: np.uint8}[code]
                values = np.empty(count, dtype=dtype)
                num_out[f] = values.ctypes.data_as(ctypes.c_void_p)
                results[f] = (values, validity)

        rc = lib.decode_block(
            payload,
            ctypes.c_size_t(len(payload)),
            ctypes.c_int64(count),
            ctypes.c_int(nfields),
            type_codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            num_out,
            valid_out,
            str_offsets,
            str_data,
            str_cap.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        if rc == 0:
            return results
        if rc == -2 and attempt == 0:
            cap_guess = max(64, len(payload))  # one field owns most bytes
            continue
        return None  # malformed: python fallback handles it
    return None


def avro_encoder(count: int, field_specs: list[tuple[int, bool]], columns: list) -> bytes | None:
    """Encode one Avro block natively. `columns` mirrors avro_decoder's
    output shapes: numeric/bool -> (values ndarray, validity ndarray|None);
    string -> (offsets int32 ndarray, data uint8 ndarray, validity|None).
    Returns the block body bytes or None if unavailable."""
    lib = _load()
    if lib is None:
        return None
    nfields = len(field_specs)
    type_codes = np.array([c for c, _ in field_specs], dtype=np.int32)
    nullable = np.array([1 if n else 0 for _, n in field_specs], dtype=np.uint8)
    num_in = (ctypes.c_void_p * nfields)()
    valid_in = (ctypes.POINTER(ctypes.c_uint8) * nfields)()
    str_offsets = (ctypes.POINTER(ctypes.c_int32) * nfields)()
    str_data = (ctypes.POINTER(ctypes.c_uint8) * nfields)()
    keep = []
    cap = 64
    for f, (code, _) in enumerate(field_specs):
        col = columns[f]
        if code == CODE_STRING:
            offsets, data, validity = col
            offsets = np.ascontiguousarray(offsets, dtype=np.int32)
            data = np.ascontiguousarray(data, dtype=np.uint8)
            keep.extend([offsets, data])
            str_offsets[f] = offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            str_data[f] = data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            cap += len(data) + count * 12
        else:
            values, validity = col
            dtype = {CODE_LONG: np.int64, CODE_FLOAT: np.float64, CODE_DOUBLE: np.float64, CODE_BOOL: np.uint8}[code]
            values = np.ascontiguousarray(values, dtype=dtype)
            keep.append(values)
            num_in[f] = values.ctypes.data_as(ctypes.c_void_p)
            cap += count * 12
        if validity is None:
            valid_in[f] = ctypes.cast(None, ctypes.POINTER(ctypes.c_uint8))
        else:
            v = np.ascontiguousarray(validity, dtype=np.uint8)
            keep.append(v)
            valid_in[f] = v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    out = np.empty(cap, dtype=np.uint8)
    n = lib.encode_block(
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_size_t(cap),
        ctypes.c_int64(count),
        ctypes.c_int(nfields),
        type_codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        nullable.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        num_in,
        valid_in,
        str_offsets,
        str_data,
    )
    if n < 0:
        return None
    return out[:n].tobytes()
