/* Native Avro block decoder.
 *
 * The pure-python codec in format/avro.py parses records byte-by-byte in the
 * interpreter; this decoder walks one decompressed Avro block in C and fills
 * columnar output buffers directly:
 *   - int-family fields  -> int64 values + uint8 validity
 *   - float/double       -> float64 values + uint8 validity
 *   - boolean            -> uint8 values + uint8 validity
 *   - string/bytes       -> int32 offsets (n+1) + contiguous data bytes +
 *                           uint8 validity (arrow StringArray layout)
 *
 * Built on demand with `cc -O3 -shared -fPIC` and loaded via ctypes
 * (paimon_tpu/native/__init__.py); the python codec is the fallback.
 *
 * Field type codes (must match native/__init__.py):
 *   0 int/long   1 float   2 double   3 boolean   4 string/bytes
 * Each field additionally carries a nullable flag (["null", T] union with
 * null as branch 0, the layout format/avro.py writes).
 */

#include <stdint.h>
#include <stddef.h>
#include <string.h>

/* zigzag varint; returns new position or (size_t)-1 on overrun */
static size_t read_long(const uint8_t *buf, size_t pos, size_t len, int64_t *out) {
    uint64_t acc = 0;
    int shift = 0;
    while (pos < len) {
        uint8_t b = buf[pos++];
        acc |= ((uint64_t)(b & 0x7f)) << shift;
        if (!(b & 0x80)) {
            *out = (int64_t)(acc >> 1) ^ -(int64_t)(acc & 1);
            return pos;
        }
        shift += 7;
        if (shift > 63) return (size_t)-1;
    }
    return (size_t)-1;
}

/* Decode `count` records of `nfields` fields from buf[0:len].
 *
 * type_codes[f], nullable[f]: per-field schema.
 * num_out[f]: int64* or double* or uint8* target (length count), or NULL for
 *             string fields.
 * valid_out[f]: uint8* validity target (length count).
 * str_offsets[f]: int32* (length count+1), only for string fields.
 * str_data[f]: uint8* contiguous string bytes target, capacity str_cap[f].
 *
 * Returns 0 on success, -1 on malformed input, -2 if a string data buffer
 * would overflow (caller retries with a larger buffer).
 */
int decode_block(const uint8_t *buf, size_t len, int64_t count, int nfields,
                 const int32_t *type_codes, const uint8_t *nullable,
                 void **num_out, uint8_t **valid_out,
                 int32_t **str_offsets, uint8_t **str_data,
                 const int64_t *str_cap) {
    size_t pos = 0;
    int64_t str_used[64];
    if (nfields > 64) return -1;
    for (int f = 0; f < nfields; f++) {
        str_used[f] = 0;
        if (type_codes[f] == 4 && str_offsets[f]) str_offsets[f][0] = 0;
    }
    for (int64_t r = 0; r < count; r++) {
        for (int f = 0; f < nfields; f++) {
            int present = 1;
            if (nullable[f]) {
                int64_t branch;
                pos = read_long(buf, pos, len, &branch);
                if (pos == (size_t)-1) return -1;
                present = branch != 0;
            }
            valid_out[f][r] = (uint8_t)present;
            switch (type_codes[f]) {
            case 0: { /* int/long */
                int64_t v = 0;
                if (present) {
                    pos = read_long(buf, pos, len, &v);
                    if (pos == (size_t)-1) return -1;
                }
                ((int64_t *)num_out[f])[r] = v;
                break;
            }
            case 1: { /* float -> double */
                double v = 0;
                if (present) {
                    if (pos + 4 > len) return -1;
                    float fv;
                    memcpy(&fv, buf + pos, 4);
                    pos += 4;
                    v = (double)fv;
                }
                ((double *)num_out[f])[r] = v;
                break;
            }
            case 2: { /* double */
                double v = 0;
                if (present) {
                    if (pos + 8 > len) return -1;
                    memcpy(&v, buf + pos, 8);
                    pos += 8;
                }
                ((double *)num_out[f])[r] = v;
                break;
            }
            case 3: { /* boolean */
                uint8_t v = 0;
                if (present) {
                    if (pos + 1 > len) return -1;
                    v = buf[pos++] ? 1 : 0;
                }
                ((uint8_t *)num_out[f])[r] = v;
                break;
            }
            case 4: { /* string/bytes */
                int64_t n = 0;
                if (present) {
                    pos = read_long(buf, pos, len, &n);
                    if (pos == (size_t)-1 || n < 0 || pos + (size_t)n > len) return -1;
                    if (str_used[f] + n > str_cap[f]) return -2;
                    if (str_used[f] + n > 0x7fffffff) return -1; /* int32 offsets */
                    memcpy(str_data[f] + str_used[f], buf + pos, (size_t)n);
                    pos += (size_t)n;
                    str_used[f] += n;
                }
                str_offsets[f][r + 1] = (int32_t)str_used[f];
                break;
            }
            default:
                return -1;
            }
        }
    }
    return 0;
}

/* Encode `count` records into out[0:out_cap]. Inputs mirror decode_block:
 * numeric columns as int64/double/uint8 arrays + validity, strings as arrow
 * offsets + contiguous data. Writes the block body (no count/size header).
 * Returns bytes written, or -1 if out_cap is too small / nfields > 64. */
static size_t write_long(uint8_t *out, size_t pos, int64_t v) {
    uint64_t z = ((uint64_t)v << 1) ^ (uint64_t)(v >> 63);
    while (z & ~0x7fULL) {
        out[pos++] = (uint8_t)(z & 0x7f) | 0x80;
        z >>= 7;
    }
    out[pos++] = (uint8_t)z;
    return pos;
}

int64_t encode_block(uint8_t *out, size_t out_cap, int64_t count, int nfields,
                     const int32_t *type_codes, const uint8_t *nullable,
                     void **num_in, uint8_t **valid_in,
                     int32_t **str_offsets, uint8_t **str_data) {
    if (nfields > 64) return -1;
    size_t pos = 0;
    /* worst case per scalar is 10 varint bytes + 1 branch byte */
    for (int64_t r = 0; r < count; r++) {
        for (int f = 0; f < nfields; f++) {
            int present = valid_in[f] ? valid_in[f][r] : 1;
            if (pos + 32 > out_cap) return -1;
            if (nullable[f]) pos = write_long(out, pos, present ? 1 : 0);
            if (!present) continue;
            switch (type_codes[f]) {
            case 0:
                pos = write_long(out, pos, ((const int64_t *)num_in[f])[r]);
                break;
            case 1: { /* float */
                float fv = (float)((const double *)num_in[f])[r];
                memcpy(out + pos, &fv, 4);
                pos += 4;
                break;
            }
            case 2:
                memcpy(out + pos, &((const double *)num_in[f])[r], 8);
                pos += 8;
                break;
            case 3:
                out[pos++] = ((const uint8_t *)num_in[f])[r] ? 1 : 0;
                break;
            case 4: {
                int32_t lo = str_offsets[f][r];
                int32_t hi = str_offsets[f][r + 1];
                int64_t n = hi - lo;
                pos = write_long(out, pos, n);
                if (pos + (size_t)n > out_cap) return -1;
                memcpy(out + pos, str_data[f] + lo, (size_t)n);
                pos += (size_t)n;
                break;
            }
            default:
                return -1;
            }
        }
    }
    return (int64_t)pos;
}
