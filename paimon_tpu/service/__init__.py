"""Remote KV point-query service.

Parity: /root/reference/paimon-service/ — the reference's only custom network
protocol: NetworkServer/NetworkClient (Netty) carrying KvRequest/KvResponse,
KvQueryServer dispatching to a TableQuery, KvQueryClient used by lookup joins
(RemoteTableQuery). Here: a threaded socket server speaking a length-prefixed
JSON protocol over TCP, dispatching to LocalTableQuery; the address registers
on the filesystem like the reference's ServiceManager address files.

Wire format (both directions): 4-byte big-endian length + UTF-8 JSON.
Request:  {"id": n, "method": "lookup", "partition": [...], "key": [...]}
          {"id": n, "method": "get_batch", "partition": [...], "keys": [[...], ...]}
          {"id": n, "method": "refresh"} | {"id": n, "method": "ping"}
          {"id": n, "method": "health"}
Response: {"id": n, "ok": true, "row": [...] | null} | {"id": n, "ok": false, "error": "..."}
          {"id": n, "ok": true, "rows": [[...] | null, ...]}
          {"id": n, "ok": false, "busy": true, "state": "...", "retry_after_ms": m}

`get_batch` is the batched serving path (LocalTableQuery.get_batch): N keys
resolve in one vectorized probe pass, read-your-writes when the server was
constructed with an attached TableWrite. It rides the same admission idea as
the ingest side: at most `lookup.get.max-inflight` concurrent get_batch
requests are admitted — the next one is answered with a TYPED busy response
(KvBusyError on the client, mirroring WriterBackpressureError/
FlightBusyError), never a queue-into-timeout.

`health` surfaces the writer admission controller's flow-control state
(core.admission.WriteBufferController.health_dict — the same stable schema
the Flight server and the soak supervisors report), so a remote ingest
frontend colocated with this query service can shed load the moment the
writer side is THROTTLING/REJECTING instead of discovering it by timeout.
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
import time
from typing import TYPE_CHECKING

from ..fs import FileIO
from ..utils import dumps, loads
from .shed import ShedError, ShedInfo

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = ["KvQueryServer", "KvQueryClient", "KvBusyError", "ServiceManager"]


class KvBusyError(ShedError):
    """The server shed a get_batch with a typed BUSY (lookup.get.max-inflight
    saturated). A serialization of service.shed.ShedInfo (kind="get_batch"):
    carries the payload and the server's retry-after hint — the read-side
    twin of the ingest path's FlightBusyError — plus the canonical
    ``shed_info`` record for shed-kind-generic callers (the gateway)."""

    default_kind = "get_batch"

    def __init__(self, payload: "dict | ShedInfo"):
        super().__init__(payload, message=f"get shed by server: {payload}")


def _send(sock: socket.socket, obj: dict) -> None:
    payload = dumps(obj).encode()
    sock.sendall(struct.pack(">I", len(payload)) + payload)


def _recv(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (length,) = struct.unpack(">I", header)
    body = _recv_exact(sock, length)
    return None if body is None else loads(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class ServiceManager:
    """Service address files on the table filesystem (reference
    core service/ServiceManager.java)."""

    PRIMARY_KEY_LOOKUP = "primary-key-lookup"

    def __init__(self, file_io: FileIO, table_path: str):
        self.file_io = file_io
        self.service_dir = f"{table_path}/service"

    def register(self, service: str, host: str, port: int) -> None:
        self.file_io.try_overwrite(f"{self.service_dir}/{service}", dumps({"host": host, "port": port}).encode())

    def address(self, service: str) -> tuple[str, int] | None:
        try:
            d = loads(self.file_io.read_bytes(f"{self.service_dir}/{service}"))
            return d["host"], d["port"]
        except Exception:
            return None

    def unregister(self, service: str) -> None:
        self.file_io.delete(f"{self.service_dir}/{service}")


class KvQueryServer:
    def __init__(
        self,
        table: "FileStoreTable",
        host: str = "127.0.0.1",
        port: int = 0,
        health_provider=None,
        table_write=None,
        max_inflight_gets: int | None = None,
        gateway=None,
    ):
        """`health_provider`: an optional zero-arg callable returning the
        flow-control dict to serve on the `health` method — typically
        `TableWrite.health` or `WriteBufferController.health_dict` of the
        ingest job colocated with this server. Without one the server
        reports a permanently-ok placeholder (it serves reads only).

        `table_write`: an optional live TableWrite whose buffered state
        get_batch serves (read-your-writes: an ingest frontend colocated
        with this server answers gets with committed-plus-buffered rows).

        `max_inflight_gets`: get_batch admission depth (default from
        lookup.get.max-inflight); the request past the cap is answered with
        a typed busy response, not queued.

        `gateway`: an optional service.gateway.Gateway. With one, get_batch
        requests carrying a `tenant` field run through the gateway's
        per-tenant admission (weighted-fair byte/inflight budgets) BEFORE
        the local inflight gate, their latency lands on the gateway's SLO
        surface, and the `slo` method serves gateway.slo()."""
        from ..options import CoreOptions
        from ..table.query import LocalTableQuery

        self.table = table
        self.query = LocalTableQuery(table)
        if table_write is not None:
            self.query.attach_write(table_write)
        self.health_provider = health_provider
        self.gateway = gateway
        if max_inflight_gets is None:
            max_inflight_gets = int(table.options.options.get(CoreOptions.LOOKUP_GET_MAX_INFLIGHT))
        self._get_gate = threading.BoundedSemaphore(max(int(max_inflight_gets), 1))
        self._lock = threading.Lock()
        query = self.query
        lock = self._lock
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv(self.request)
                    if req is None:
                        return
                    rid = req.get("id")
                    try:
                        method = req["method"]
                        if method == "ping":
                            _send(self.request, {"id": rid, "ok": True})
                        elif method == "health":
                            h = (
                                outer.health_provider()
                                if outer.health_provider is not None
                                else {"state": "ok"}
                            )
                            _send(self.request, {"id": rid, "ok": True, "health": h})
                        elif method == "slo":
                            s = outer.gateway.slo() if outer.gateway is not None else {}
                            _send(self.request, {"id": rid, "ok": True, "slo": s})
                        elif method == "refresh":
                            with lock:
                                query.refresh()
                            _send(self.request, {"id": rid, "ok": True})
                        elif method == "lookup":
                            with lock:
                                row = query.lookup(tuple(req.get("partition", ())), tuple(req["key"]))
                            _send(
                                self.request,
                                {"id": rid, "ok": True, "row": None if row is None else list(row.to_pylist()[0])},
                            )
                        elif method == "get_batch":
                            gw_tenant = None
                            if outer.gateway is not None:
                                gw_tenant, shed = outer.gateway.admit(
                                    req.get("tenant"), "get_batch"
                                )
                                if shed is not None:
                                    from ..metrics import soak_metrics

                                    soak_metrics().counter("shed_requests").inc()
                                    _send(
                                        self.request,
                                        {"id": rid, "ok": False, **shed.to_payload()},
                                    )
                                    continue
                            if not outer._get_gate.acquire(blocking=False):
                                # typed BUSY: the admission depth is
                                # saturated — shed NOW, never queue the
                                # client into a timeout
                                from ..metrics import get_metrics, soak_metrics

                                if gw_tenant is not None:
                                    outer.gateway.release(gw_tenant)
                                get_metrics().counter("busy_rejected").inc()
                                soak_metrics().counter("shed_requests").inc()
                                info = ShedInfo(
                                    kind="get_batch",
                                    state="busy-reads",
                                    tenant=gw_tenant,
                                    retry_after_ms=25,
                                )
                                _send(
                                    self.request,
                                    {"id": rid, "ok": False, **info.to_payload()},
                                )
                                continue
                            t0 = time.perf_counter()
                            try:
                                ks = [tuple(k) if isinstance(k, list) else (k,) for k in req["keys"]]
                                with lock:
                                    res = query.get_batch(ks, tuple(req.get("partition", ())))
                                rows = [None if r is None else list(r) for r in res.to_pylist()]
                            finally:
                                outer._get_gate.release()
                                if gw_tenant is not None:
                                    outer.gateway.release(gw_tenant)
                                    outer.gateway.observe(gw_tenant, "get_batch", t0)
                            _send(self.request, {"id": rid, "ok": True, "rows": rows})
                        else:
                            _send(self.request, {"id": rid, "ok": False, "error": f"unknown method {method}"})
                    except Exception as e:  # noqa: BLE001 — surface to the client
                        _send(self.request, {"id": rid, "ok": False, "error": str(e)})

        self._server = socketserver.ThreadingTCPServer((host, port), Handler, bind_and_activate=True)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[0], self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> tuple[str, int]:
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        ServiceManager(self.table.file_io, self.table.path).register(
            ServiceManager.PRIMARY_KEY_LOOKUP, self.host, self.port
        )
        return self.host, self.port

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        ServiceManager(self.table.file_io, self.table.path).unregister(ServiceManager.PRIMARY_KEY_LOOKUP)


class KvQueryClient:
    """Blocking client (reference KvQueryClient + RemoteTableQuery)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._id = 0
        self._lock = threading.Lock()

    @staticmethod
    def for_table(table: "FileStoreTable") -> "KvQueryClient":
        addr = ServiceManager(table.file_io, table.path).address(ServiceManager.PRIMARY_KEY_LOOKUP)
        if addr is None:
            raise ConnectionError("no primary-key-lookup service registered for this table")
        return KvQueryClient(*addr)

    def _call(self, method: str, **kw) -> dict:
        with self._lock:
            self._id += 1
            _send(self._sock, {"id": self._id, "method": method, **kw})
            resp = _recv(self._sock)
        if resp is None:
            raise ConnectionError("server closed the connection")
        if not resp.get("ok"):
            if resp.get("busy"):
                raise KvBusyError(resp)
            raise RuntimeError(resp.get("error", "unknown server error"))
        return resp

    def ping(self) -> bool:
        return self._call("ping")["ok"]

    def health(self) -> dict:
        """The server's writer flow-control state (admission health_dict
        schema): callers shed/back off on state != 'ok' instead of timing
        out against a saturated writer."""
        return self._call("health")["health"]

    def refresh(self) -> None:
        self._call("refresh")

    def lookup(self, partition: tuple, key) -> tuple | None:
        if not isinstance(key, tuple):
            key = (key,)
        row = self._call("lookup", partition=list(partition), key=list(key)).get("row")
        return None if row is None else tuple(row)

    def get_batch(self, keys, partition: tuple = (), tenant: str | None = None) -> list:
        """Batched gets: list[tuple | None] aligned with `keys`. Raises
        KvBusyError (typed, with retry_after_ms) when the server shed the
        request under read overload — callers back off, never time out.
        `tenant` tags the request for a gateway-fronted server's per-tenant
        admission (untagged rides the "default" tenant budget)."""
        ks = [list(k) if isinstance(k, (tuple, list)) else [k] for k in keys]
        kw = {"partition": list(partition), "keys": ks}
        if tenant is not None:
            kw["tenant"] = tenant
        rows = self._call("get_batch", **kw)["rows"]
        return [None if r is None else tuple(r) for r in rows]

    def slo(self) -> dict:
        """The gateway SLO surface of a gateway-fronted server (empty dict
        when the server has no gateway attached)."""
        return self._call("slo")["slo"]

    def close(self) -> None:
        self._sock.close()
