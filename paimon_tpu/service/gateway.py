"""Unified multi-tenant gateway: one front door for every request kind.

The service plane grew organically — the KV server, Flight
do_put/do_get/do_action, subscription long-polls, the cluster client, SQL
scatter-gather — each with its own typed BUSY and no notion of *who* is
calling. ``Gateway`` is the consolidation (ROADMAP item 4): put, get_batch,
subscribe poll, and SQL (local ``sql.select.query`` and distributed
``sql.cluster.cluster_query``) all enter through one object that

  1. ADMITS through shared per-tenant QoS (service.qos): token/byte budgets
     with weighted-fair refill (`gateway.tenant.<id>.{weight,max-inflight,
     bytes-per-sec}`; untagged traffic lands in the "default" tenant), the
     PR 8 WriteBufferController idea generalized from memtable bytes to
     request bytes. A refusal is ALWAYS one canonical typed shed
     (service.shed.ShedInfo carried by GatewayShedError) — the legacy
     KvBusyError / FlightBusyError / SubscriberShedError are serializations
     of the same record.
  2. HEDGES the read path: a point-get or scan-fragment whose primary
     (owning worker, PR 15/16 routing) misses `gateway.hedge.deadline-ms`
     is re-issued to a secondary live non-owner worker, which serves the
     same committed snapshot from the shared filesystem through its
     existing LocalTableQuery / scan_frag path (snapshot-pinned, so the
     answers are bit-identical). First non-BUSY answer wins; the loser's
     dedicated connection is cancelled (socket shutdown aborts its blocked
     recv) and counted. Hedges are bounded by `gateway.hedge.max-fraction`
     of hedgeable requests so a cluster-wide brownout cannot double every
     read.
  3. OBSERVES everything: the gateway{...} metric group plus the per-tenant
     SLO surface ``Gateway.slo()`` (p50/p99 per request kind from decayed
     histograms, admitted/shed/hedged counts, budget utilization,
     retry_after hints) that the KV and Flight servers expose as a
     health-style "slo" action.

Full replica *ownership* (a hot bucket with a second writer) stays ROADMAP
item 2 — hedging needs only the shared-FS read path that already exists.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout
from concurrent.futures import wait as _fut_wait

from .qos import DEFAULT_TENANT, QosController, SloTracker
from .shed import GatewayShedError, ShedInfo

__all__ = ["Gateway", "GatewayShedError"]


class _HedgeAttempt:
    """One in-flight RPC attempt on a dedicated connection: the conn is
    registered under a lock so a canceller in another thread can abort the
    blocked recv (conn.cancel()) without racing the happy-path checkin."""

    __slots__ = ("future", "conn", "cancelled", "lock", "wid")

    def __init__(self, wid: int):
        self.future = None
        self.conn = None
        self.cancelled = False
        self.lock = threading.Lock()
        self.wid = wid


class _ConnPool:
    """Per-worker stacks of DEDICATED _RpcConn connections for hedged
    calls. Dedicated (never the ClusterClient's shared conns) because
    cancellation closes the socket mid-call — poisoning a shared routing
    connection would fail unrelated traffic."""

    def __init__(self, addr_of):
        self._addr_of = addr_of  # wid -> (host, port)
        self._lock = threading.Lock()
        self._free: dict[int, list] = {}

    def checkout(self, wid: int):
        from .cluster import _RpcConn

        with self._lock:
            stack = self._free.get(wid)
            if stack:
                return stack.pop()
        return _RpcConn(*self._addr_of(wid))

    def checkin(self, wid: int, conn) -> None:
        with self._lock:
            self._free.setdefault(wid, []).append(conn)

    def discard(self, conn) -> None:
        conn.close()

    def close(self) -> None:
        with self._lock:
            conns = [c for stack in self._free.values() for c in stack]
            self._free.clear()
        for c in conns:
            c.close()


class Gateway:
    """The front door for one table (and its catalog / cluster route).

    ``client`` is an optional service.cluster.ClusterClient: with it,
    get_batch routes to owning workers (hedged) and SQL scatters through
    cluster_query with hedged scan fragments; without it, both serve
    locally. Every public method takes ``tenant=`` (None = "default") and
    either returns the answer or raises GatewayShedError carrying the
    canonical ShedInfo."""

    def __init__(self, table, catalog=None, client=None, options=None):
        from ..core.admission import WriteBufferController
        from ..options import CoreOptions

        self._table = table
        self._catalog = catalog
        self._client = client
        opts = table.store.options.options.copy()
        if options is not None:
            opts.update(options)
        self._options = opts
        self._qos = QosController(opts)
        tau_ms = int(opts.get(CoreOptions.GATEWAY_SLO_DECAY_WINDOW))
        self._slo = SloTracker(tau_s=max(tau_ms, 1) / 1000.0)
        self._hedge_enabled = bool(opts.get(CoreOptions.GATEWAY_HEDGE_ENABLED))
        self._hedge_deadline_ms = int(opts.get(CoreOptions.GATEWAY_HEDGE_DEADLINE))
        self._hedge_max_fraction = float(opts.get(CoreOptions.GATEWAY_HEDGE_MAX_FRACTION))
        self._retry_after_ms = int(opts.get(CoreOptions.GATEWAY_RETRY_AFTER))
        # put plane: one shared admission controller under one commit lock
        # (single-committer discipline, the flight server's do_put shape)
        self._write_ctrl = WriteBufferController.from_options(table.store.options)
        self._put_lock = threading.Lock()
        self._put_tables: dict[str, object] = {}  # commit_user -> handle
        # local read plane (no cluster route)
        self._query = None
        self._query_lock = threading.Lock()
        # subscriptions
        self._hub = None
        self._own_hub = False
        self._subs: dict[str, object] = {}
        self._subs_lock = threading.Lock()
        self._sub_seq = 0
        # hedging
        self._pool = _ConnPool(client.addr_of) if client is not None else None
        # RPC attempts are blocked-on-socket, not CPU: the pool must cover
        # the full admitted concurrency (tenant inflight caps gate demand
        # upstream). A CPU-sized pool queues primaries, the queue wait eats
        # the hedge deadline, and every queued request then hedges into the
        # same saturated pool — a self-amplifying collapse under fan-in.
        self._executor = ThreadPoolExecutor(max_workers=256, thread_name_prefix="paimon-gw")
        self._hedge_lock = threading.Lock()
        self._hedge_requests = 0
        self._hedges_issued = 0
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        self._closed = False

    # ------------------------------------------------------------------
    # shared admission plumbing
    def _metrics(self):
        from ..metrics import gateway_metrics

        return gateway_metrics()

    def _admit(self, tenant: "str | None", kind: str, nbytes: int = 0) -> str:
        g = self._metrics()
        g.counter("requests").inc()
        name, shed = self._qos.admit(tenant, kind, nbytes)
        if shed is not None:
            g.counter("sheds_typed").inc()
            self._slo.record_shed(name, kind)
            raise GatewayShedError(shed)
        g.counter("admitted").inc()
        return name

    def _record(self, tenant: str, kind: str, t0: float, hedged: bool = False) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        self._slo.record(tenant, kind, ms, hedged=hedged)
        self._metrics().histogram(f"{kind}_ms").update(ms)

    def _count_untyped(self, exc: BaseException) -> None:
        """The acceptance invariant gateway{sheds_untyped} == 0: a pressure
        signal escaping the gateway in any shape other than GatewayShedError
        — a raw legacy ShedError the conversion missed, or an infra error
        (timeout / dead connection) standing in for a shed — is an untyped
        shed. User errors (bad SQL, unknown sub id) are not sheds."""
        from .shed import ShedError

        if isinstance(exc, (GatewayShedError, FileNotFoundError)):
            # FileNotFoundError is a user error (missing table/path), not
            # pressure — despite being an OSError
            return
        if isinstance(exc, (ShedError, TimeoutError, ConnectionError, OSError)):
            self._metrics().counter("sheds_untyped").inc()

    # ------------------------------------------------------------------
    # embedding-server seam: the KV/Flight front doors share this gateway's
    # tenant budgets and SLO surface for requests that never enter the
    # in-process put/get_batch paths
    def admit(self, tenant: "str | None", kind: str, nbytes: int = 0):
        """Non-raising admission for an embedding server: returns
        (resolved_tenant, ShedInfo | None), counted into gateway{...}
        exactly like the in-process paths. Pair every admitted request
        with release(tenant); observe(tenant, kind, t0) records latency."""
        try:
            return self._admit(tenant, kind, nbytes), None
        except GatewayShedError as e:
            return e.shed_info.tenant or DEFAULT_TENANT, e.shed_info

    def release(self, tenant: "str | None") -> None:
        self._qos.release(tenant)

    def observe(self, tenant: str, kind: str, t0: float, hedged: bool = False) -> None:
        self._record(tenant, kind, t0, hedged=hedged)

    # ------------------------------------------------------------------
    # put
    def _put_table(self, user: "str | None"):
        """The table handle a put commits through: the gateway's own handle
        by default, or a cached per-`user` handle when the caller supplies a
        commit identity (journaled writers recover via find_landed_append,
        which needs the commit_user on the snapshot)."""
        if user is None:
            return self._table
        t = self._put_tables.get(user)
        if t is None:
            t = self._put_tables[user] = self._table.with_user(user)
        return t

    def put(
        self,
        data,
        kinds=None,
        tenant: "str | None" = None,
        user: "str | None" = None,
        identifier: "int | None" = None,
    ):
        """Write one batch and commit it. Backpressure from the shared
        write-buffer controller surfaces as a typed GatewayShedError (never
        an untyped unwind, even when close() re-raises during teardown).

        `user`/`identifier` give the commit a caller-owned identity: the
        snapshot records (user, identifier), so an intent/ack-journaled
        client that loses the response can resolve whether the round landed
        from the chain alone. With an identifier the return value is the
        landed APPEND snapshot id (None when nothing committed) instead of
        the row count."""
        from ..core.admission import WriterBackpressureError
        from ..data.batch import ColumnBatch
        from ..table.write import TableWrite

        if isinstance(data, dict):
            data = ColumnBatch.from_pydict(self._table.row_type, data)
        name = self._admit(tenant, "put", data.byte_size())
        t0 = time.perf_counter()
        sid = None
        try:
            with self._put_lock:
                table = self._put_table(user)
                tw = TableWrite(table, buffer_controller=self._write_ctrl)
                try:
                    tw.write(data, kinds)
                    msgs = tw.prepare_commit()
                finally:
                    try:
                        tw.close()
                    except WriterBackpressureError:
                        # teardown must not replace the typed shed already
                        # unwinding (ISSUE 17 bugfix hunt, the do_put shape)
                        pass
                if identifier is None:
                    table.new_batch_write_builder().new_commit().commit(msgs)
                else:
                    from ..core.manifest import ManifestCommittable

                    sids = table.store.new_commit().commit(
                        ManifestCommittable(identifier, messages=msgs)
                    )
                    sid = sids[0] if sids else None
        except WriterBackpressureError as e:
            health = self._write_ctrl.health_dict() if self._write_ctrl else {}
            self._metrics().counter("sheds_typed").inc()
            self._slo.record_shed(name, "put")
            raise GatewayShedError(
                ShedInfo(
                    kind="put",
                    state=health.get("state", "rejecting"),
                    tenant=name,
                    retry_after_ms=int(health.get("retry_after_ms") or 25),
                )
            ) from e
        except BaseException as e:
            self._count_untyped(e)
            raise
        finally:
            self._qos.release(name)
        self._record(name, "put", t0)
        return len(data) if identifier is None else sid

    # ------------------------------------------------------------------
    # get_batch
    def get_batch(self, keys, partition: tuple = (), tenant: "str | None" = None) -> list:
        """list[tuple | None] aligned with `keys` — served by the owning
        workers (hedged past the deadline) or a local LocalTableQuery."""
        ks = [k if isinstance(k, tuple) else (k,) for k in keys]
        name = self._admit(tenant, "get_batch", len(ks) * 64)
        t0 = time.perf_counter()
        hedged_before = self._hedges_for_kind()
        try:
            if self._client is None:
                out = self._local_get(ks, partition)
            else:
                out = self._routed_get(ks, partition)
        except BaseException as e:
            self._count_untyped(e)
            raise
        finally:
            self._qos.release(name)
        self._record(name, "get_batch", t0, hedged=self._hedges_for_kind() > hedged_before)
        return out

    def _hedges_for_kind(self) -> int:
        with self._hedge_lock:
            return self._hedges_issued

    def _local_get(self, ks, partition) -> list:
        from ..table.query import LocalTableQuery

        with self._query_lock:
            if self._query is None:
                self._query = LocalTableQuery(self._table)
            self._query.refresh()
            res = self._query.get_batch(ks, tuple(partition))
        return [None if r is None else tuple(r) for r in res.to_pylist()]

    def _routed_get(self, ks, partition) -> list:
        from ..data.batch import ColumnBatch
        from ..table.bucket import bucket_ids

        client = self._client
        store = self._table.store
        key_schema = store.value_schema.project(store.key_names)
        probe = ColumnBatch.from_pydict(
            key_schema,
            {name: [k[i] for k in ks] for i, name in enumerate(store.key_names)},
        )
        buckets = bucket_ids(probe, self._table.schema.bucket_keys, client.num_buckets)
        out: list = [None] * len(ks)
        by_wid: dict[int, list[int]] = {}
        wid_bucket_keys: dict[int, dict[int, int]] = {}
        for i, b in enumerate(buckets.tolist()):
            wid = self._owner_for(int(b))
            by_wid.setdefault(wid, []).append(i)
            counts = wid_bucket_keys.setdefault(wid, {})
            counts[int(b)] = counts.get(int(b), 0) + 1
        for wid, idxs in by_wid.items():
            # Hedge/failover hint: the bucket carrying the most keys in this
            # worker's group. Best-effort for mixed-bucket batches — any
            # worker serves any key off the shared FS, so reads stay correct;
            # only the replica-first warm-view preference is approximate.
            counts = wid_bucket_keys[wid]
            hint = max(counts, key=counts.get) if counts else None
            r = self._rpc_failover(
                wid,
                "get_batch",
                _bucket=hint,
                keys=[list(ks[i]) for i in idxs],
                partition=list(partition),
            )
            if r.get("busy"):
                raise GatewayShedError(ShedInfo.from_payload(r, kind="get_batch"))
            for i, row in zip(idxs, r["rows"]):
                out[i] = None if row is None else tuple(row)
        return out

    # ------------------------------------------------------------------
    # subscribe
    def _hub_acquire(self):
        from .subscription import SubscriptionHub

        if self._hub is None or self._hub._stop.is_set():
            path = self._table.store.table_path
            with SubscriptionHub._hubs_lock:
                existing = SubscriptionHub._hubs.get(path)
            # only close on teardown what this gateway actually created — a
            # colocated worker server may own the process-wide hub
            self._own_hub = existing is None or existing._stop.is_set()
            self._hub = SubscriptionHub.for_table(self._table)
        return self._hub

    def subscribe_open(
        self,
        consumer_id: "str | None" = None,
        from_snapshot: "int | None" = None,
        tenant: "str | None" = None,
    ) -> str:
        """Open a changelog subscription; returns the gateway sub id."""
        from .subscription import SubscriberShedError

        name = self._admit(tenant, "subscribe")
        try:
            try:
                sub = self._hub_acquire().subscribe(
                    consumer_id=consumer_id, from_snapshot=from_snapshot
                )
            except SubscriberShedError as e:
                self._metrics().counter("sheds_typed").inc()
                self._slo.record_shed(name, "subscribe")
                info = ShedInfo.from_payload(e.payload, kind="subscribe")
                info.tenant = name
                raise GatewayShedError(info) from e
            with self._subs_lock:
                self._sub_seq += 1
                sid = f"g{self._sub_seq}"
                self._subs[sid] = sub
            return sid
        except BaseException as e:
            self._count_untyped(e)
            raise
        finally:
            self._qos.release(name)

    def subscribe_poll(
        self, sub_id: str, timeout_ms: int = 1000, tenant: "str | None" = None
    ) -> dict:
        """One long-poll: {rows, snapshot_id, checkpoint} (rows prefixed
        with the RowKind short string, the worker-server wire shape). A shed
        subscriber surfaces as GatewayShedError carrying restart_offset —
        the durable resume position."""
        from ..types import RowKind
        from .subscription import SubscriberShedError

        with self._subs_lock:
            sub = self._subs.get(sub_id)
        if sub is None:
            raise ValueError(f"unknown subscription {sub_id!r}")
        name = self._admit(tenant, "subscribe")
        t0 = time.perf_counter()
        try:
            try:
                batch = sub.poll(timeout=float(timeout_ms) / 1000.0)
            except SubscriberShedError as e:
                with self._subs_lock:
                    self._subs.pop(sub_id, None)
                self._metrics().counter("sheds_typed").inc()
                self._slo.record_shed(name, "subscribe")
                info = ShedInfo.from_payload(e.payload, kind="subscribe")
                info.tenant = name
                raise GatewayShedError(info) from e
        except BaseException as e:
            self._count_untyped(e)
            raise
        finally:
            self._qos.release(name)
        self._record(name, "subscribe", t0)
        if batch is None:
            return {"rows": [], "snapshot_id": None, "checkpoint": sub.checkpoint}
        rows = [
            [RowKind(int(k)).short_string, *r]
            for r, k in zip(batch.data.to_pylist(), batch.kinds.tolist())
        ]
        return {"rows": rows, "snapshot_id": batch.snapshot_id, "checkpoint": sub.checkpoint}

    def subscribe_close(self, sub_id: str, delete_consumer: bool = False) -> None:
        with self._subs_lock:
            sub = self._subs.pop(sub_id, None)
        if sub is not None:
            sub.close(delete_consumer=delete_consumer)

    # ------------------------------------------------------------------
    # SQL
    def sql(self, statement: str, tenant: "str | None" = None):
        """Execute one SELECT (or EXPLAIN SELECT) — distributed through the
        cluster route when a client is attached (scan fragments hedged),
        locally otherwise. Returns the result ColumnBatch.

        Hedging composes with shuffle aggregation (ISSUE 20) untouched: a
        hedged shuffle-mode fragment may run on two workers, but partial
        content is deterministic and exchange delivery is keyed
        (qid, range, src), so the duplicate's parts overwrite bit-identical
        bytes at the range owners — never double-counted."""
        if self._catalog is None:
            raise ValueError("gateway has no catalog: SQL routing needs one")
        name = self._admit(tenant, "sql", len(statement))
        t0 = time.perf_counter()
        hedged_before = self._hedges_for_kind()
        try:
            if self._client is not None:
                from ..sql.cluster import cluster_query

                try:
                    out = cluster_query(
                        self._catalog,
                        statement,
                        self._client,
                        scan_frag_fn=self.hedged_scan_frag,
                    )
                except (ConnectionError, TimeoutError, OSError) as e:
                    if isinstance(e, FileNotFoundError):
                        raise  # user error (missing table/path), not a dead route
                    # the whole worker pool mid-respawn: fragment planning
                    # found no live route — pressure, typed like every other
                    # route escape (the sql client backs off on retry_after)
                    self._metrics().counter("sheds_typed").inc()
                    self._slo.record_shed(name, "sql")
                    raise GatewayShedError(
                        ShedInfo(
                            kind="sql",
                            state="route-respawning",
                            tenant=name,
                            retry_after_ms=max(int(self._retry_after_ms), 1),
                        )
                    ) from e
            else:
                from ..sql.select import query

                out = query(self._catalog, statement)
        except BaseException as e:
            self._count_untyped(e)
            raise
        finally:
            self._qos.release(name)
        self._record(name, "sql", t0, hedged=self._hedges_for_kind() > hedged_before)
        return out

    # ------------------------------------------------------------------
    # hedging
    def _secondary_for(self, primary: int, bucket: "int | None" = None) -> "int | None":
        candidates = [w for w in self._client.live_workers() if w != primary]
        if not candidates:
            return None
        if bucket is not None:
            # replica-first: a secondary owner of this bucket serves its gets
            # from a warm local view, so the hedge lands on the cheapest host
            reps = [w for w in self._client.replicas_of(int(bucket)) if w != primary and w in candidates]
            if reps:
                return reps[0]
        # deterministic: the next live worker after the primary, cyclically
        later = [w for w in candidates if w > primary]
        return (later or candidates)[0]

    def _submit(self, wid: int, method: str, kw: dict) -> _HedgeAttempt:
        task = _HedgeAttempt(wid)
        pool = self._pool

        def run():
            conn = pool.checkout(wid)
            with task.lock:
                if task.cancelled:
                    pool.discard(conn)
                    raise ConnectionError("hedge attempt cancelled before dispatch")
                task.conn = conn
            try:
                r = conn.call(method, **kw)
            except BaseException:
                # clear ownership under the lock BEFORE closing: _cancel
                # shuts down whatever task.conn points at, and this fd is
                # about to be freed for reuse
                with task.lock:
                    task.conn = None
                pool.discard(conn)
                raise
            with task.lock:
                task.conn = None
                if task.cancelled:
                    # cancel raced the reply: the socket may already be
                    # half-shut — never return it to the pool
                    pool.discard(conn)
                else:
                    pool.checkin(wid, conn)
            return r

        with self._inflight_cond:
            self._inflight += 1
        task.future = self._executor.submit(run)
        task.future.add_done_callback(self._attempt_done)
        return task

    def _attempt_done(self, fut) -> None:
        fut.exception()  # consume, never let a cancelled loser warn
        with self._inflight_cond:
            self._inflight -= 1
            self._inflight_cond.notify_all()

    def _cancel(self, task: _HedgeAttempt) -> None:
        with task.lock:
            task.cancelled = True
            if task.conn is not None:
                # under task.lock: the attempt thread clears task.conn
                # (under this same lock) before it discards or checks the
                # connection in, so a non-None conn here still owns its fd —
                # shutdown is safe, unblocks its recv, and the attempt
                # thread does the close
                task.conn.cancel()
        self._metrics().counter("hedges_cancelled").inc()

    def hedge_inflight(self) -> int:
        """In-flight hedge-pool RPC attempts (winners and losers) — drains
        to 0 once every loser's teardown completed."""
        with self._inflight_cond:
            return self._inflight

    def wait_hedges_drained(self, timeout_s: float = 10.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
            return True

    def _hedged_rpc(self, primary_wid: int, method: str, _bucket: "int | None" = None, **kw) -> dict:
        """One worker RPC with tail-latency hedging. Returns the first
        non-BUSY response; a BUSY payload only when every attempt answered
        BUSY. Raises like _RpcConn.call when all attempts fail. `_bucket`
        is a routing hint only (never sent on the wire): hedges for a
        replicated bucket go replica-first."""
        g = self._metrics()
        with self._hedge_lock:
            self._hedge_requests += 1
        primary = self._submit(primary_wid, method, kw)
        if not self._hedge_enabled:
            return primary.future.result()
        try:
            return primary.future.result(timeout=self._hedge_deadline_ms / 1000.0)
        except _FutTimeout:
            pass
        except Exception:
            raise
        secondary_wid = self._secondary_for(primary_wid, bucket=_bucket)
        allowed = False
        if secondary_wid is not None:
            with self._hedge_lock:
                if self._hedges_issued + 1 <= self._hedge_max_fraction * self._hedge_requests:
                    self._hedges_issued += 1
                    allowed = True
        if not allowed:
            return primary.future.result()
        g.counter("hedges_issued").inc()
        secondary = self._submit(secondary_wid, method, kw)
        attempts = (primary, secondary)
        while True:
            for task, other in ((primary, secondary), (secondary, primary)):
                f = task.future
                if not f.done():
                    continue
                try:
                    r = f.result()
                except Exception:
                    continue
                if not r.get("busy"):
                    self._cancel(other)
                    if task is secondary:
                        g.counter("hedges_won").inc()
                    return r
            if primary.future.done() and secondary.future.done():
                # no winner: both BUSY and/or failed — a BUSY payload beats
                # an exception (the caller's retry loop owns the backoff)
                for task in attempts:
                    try:
                        return task.future.result()
                    except Exception:
                        continue
                return primary.future.result()  # re-raises the primary error
            _fut_wait(
                [t.future for t in attempts if not t.future.done()],
                return_when=FIRST_COMPLETED,
            )

    def _owner_for(self, bucket: int) -> int:
        """The worker a bucket's gets route to. A bucket with no serving
        owner (its worker was killed and hasn't re-registered) falls back to
        any live worker — get_batch serves any bucket from the shared
        filesystem — counting a route_failover; with NO live worker the
        escape is the typed 'route-respawning' shed, never a raw KeyError."""
        client = self._client
        try:
            # replica-aware: round-robins over the primary plus any granted
            # read replicas — hot buckets spread their serve load
            return client.serving_owner_of(bucket)
        except (KeyError, ConnectionError):
            live = client.live_workers()
            if live:
                self._metrics().counter("route_failovers").inc()
                return live[bucket % len(live)]
        self._metrics().counter("sheds_typed").inc()
        raise GatewayShedError(
            ShedInfo(
                kind="get_batch",
                state="route-respawning",
                retry_after_ms=max(int(self._retry_after_ms), 1),
            )
        )

    def _rpc_failover(self, wid: int, method: str, _bucket: "int | None" = None, **kw) -> dict:
        """_hedged_rpc hardened against a dead route: a connection-grain
        failure (the worker is mid-respawn, so its socket refuses or resets
        before the hedge deadline even starts) refreshes the route and
        retries on the next live worker — any live worker serves the same
        pinned snapshot from the shared filesystem, so the answer is
        bit-identical. When no worker answers, the escape is a TYPED
        'route-respawning' shed carrying the configured gateway.retry-after-ms
        (always positive), never a raw ConnectionError: the acceptance
        invariant gateway{sheds_untyped} == 0 must hold across respawns."""
        last: "BaseException | None" = None
        for _ in range(3):
            try:
                return self._hedged_rpc(wid, method, _bucket=_bucket, **kw)
            except FileNotFoundError:
                raise  # user error (missing table/path), not a dead route
            except (ConnectionError, TimeoutError, OSError) as e:
                last = e
                self._metrics().counter("route_failovers").inc()
                try:
                    self._client.refresh_route()
                except Exception:
                    pass
                # a respawned worker re-registers under the same wid with a
                # fresh address, so the primary stays a candidate; otherwise
                # prefer a replica of the touched bucket, then step to the
                # next live worker cyclically
                alt = self._secondary_for(wid, bucket=_bucket)
                if alt is not None:
                    wid = alt
        self._metrics().counter("sheds_typed").inc()
        raise GatewayShedError(
            ShedInfo(
                kind=method,
                state="route-respawning",
                retry_after_ms=max(int(self._retry_after_ms), 1),
            )
        ) from last

    def hedged_scan_frag(self, wid: int, frag: dict, busy_wait_s: float = 10.0) -> dict:
        """ClusterClient.scan_frag's contract (BUSY absorbed with the
        server-advertised backoff) over the hedged RPC path — the
        scan_frag_fn seam sql.cluster._scatter dispatches through."""
        deadline = time.monotonic() + busy_wait_s
        while True:
            r = self._rpc_failover(wid, "scan_frag", frag=frag)
            if not r.get("busy"):
                return r["partial"]
            if time.monotonic() >= deadline:
                raise TimeoutError(f"worker {wid} still BUSY after {busy_wait_s}s")
            time.sleep(float(r.get("retry_after_ms", 50)) / 1000.0)

    # ------------------------------------------------------------------
    # SLO surface
    def slo(self) -> dict:
        """The per-tenant SLO surface: {tenants: {tenant: {kinds: {kind:
        {p50_ms, p99_ms, samples, admitted, shed, hedged}}, budget: {...}}},
        hedge: {...}} — also exported by the KV/Flight servers as the 'slo'
        health-style action."""
        with self._hedge_lock:
            hedge = {
                "enabled": self._hedge_enabled,
                "deadline_ms": self._hedge_deadline_ms,
                "max_fraction": self._hedge_max_fraction,
                "hedgeable_requests": self._hedge_requests,
                "hedges_issued": self._hedges_issued,
            }
        hedge["inflight"] = self.hedge_inflight()
        return {"tenants": self._slo.slo(self._qos), "hedge": hedge}

    # ------------------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._subs_lock:
            subs = list(self._subs.values())
            self._subs.clear()
        for sub in subs:
            try:
                sub.close()
            except Exception:
                pass
        if self._hub is not None and self._own_hub:
            try:
                self._hub.close()
            except Exception:
                pass
        self._hub = None
        if self._query is not None:
            try:
                self._query.unfollow()
            except Exception:
                pass
        self._executor.shutdown(wait=True)
        if self._pool is not None:
            self._pool.close()

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
