"""Production mega-soak: every plane on one table set, one chaos store,
one oracle, one verdict.

Each plane has its own soak (verify.sh: soak / proc-soak / subscribe /
cluster / get / gateway) — but they never run TOGETHER, so cross-plane
interactions (snapshot expiry racing a subscriber pin, a compaction drain
holding debt charges through a worker respawn, a gateway put conflicting
with a coordinator commit) go untested. This supervisor stands up the full
stack against ONE warehouse on the composed chaos store (faults over
latency over local disk, fs/testing.py) and ends with ONE verdict:

  mega supervisor (this process)
  ├── ClusterCoordinator (in-process: commits, reassignment, adaptive
  │     compaction drain)                                 [cluster cells]
  ├── Gateway + GatewayServer (TCP front door: ≥3 tenants, hedged reads,
  │     route failover, journaled puts)
  ├── cluster worker OS procs   — mesh ingest + serving    [cluster cells]
  ├── direct writer OS procs    — proc_soak protocol       [kv cells]
  ├── gateway writer OS procs   — intent/ack journal, puts THROUGH the
  │     gateway wire (commit identity rides the RPC)
  ├── getter OS procs           — get_batch through the gateway, checking
  │     the writer-id value invariant
  ├── SQL client OS procs       — aggregates + JOINs through the gateway
  ├── subscriber OS procs       — one CDC wire format per cell, journaling
  │     parse∘format round-trips
  ├── reader OS procs           — pinned-snapshot scans (proc_soak reader)
  └── churn threads             — snapshot expiry, consumer expiry, orphan
        sweep, tag/branch creation, an in-process gateway subscriber

A seeded kill schedule SIGKILLs every process kind across all registered
crash points (resilience.faults.ALL_CRASH_POINTS — the per-kind spec
queues below cover all nine, four kinds); every death is respawned and
journal-recovered per the PR 9/15 protocol. The scenario matrix axes:
schema shape (bigint k/v, dict-string PK, wide mixed), bucket mode (fixed
+ dynamic), branches/tags, consumer expiry, CDC wire format, and engine
toggles (pallas sort, mesh merge, dict-domain merge, native manifest
codec, lane compression off).

End of each cell, on the HEALED store: one fold_landed_rounds call over
every plane's journals (user_prefix is a tuple — direct, cluster, and
gateway writers fold together in snapshot-id order), verify_table_state
(full compact → scan == fold → total_record_count == unique keys →
threshold-0 sweep → disk set == reachable closure), subscriber journal
fold == pinned scan at each checkpoint, a quiesced SQL bit-identity
battery (gateway SQL twice + local query once, byte-equal), tag/branch
time travel vs the fold-up-to-tag, and consumer-expiry liveness. The run
verdict is the AND over cells, plus per-plane counters and a metric-group
census (io/soak/get/sub/cluster/sql/gateway/compaction/dict/pallas must
all be nonzero somewhere in the matrix).

Run directly:  python -m paimon_tpu.service.mega_soak [base_dir] [flags]
Child roles:   python -m paimon_tpu.service.mega_soak gateway-writer|getter|sql-client ...
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import subprocess
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field

import numpy as np

from . import _recv, _send
from .proc_soak import WriterJournal
from .soak import KEYSPACE, SCHEMA

__all__ = [
    "MegaScenario",
    "MegaConfig",
    "GatewayServer",
    "MegaSoakSupervisor",
    "run_mega_soak",
    "DEFAULT_MATRIX",
    "DEFAULT_MEGA_KILLS",
    "GW_USER_PREFIX",
    "MEGA_USER_PREFIXES",
]

GW_USER_PREFIX = "mega-gw"
# gateway writer w owns keys [(GW_KEY_BASE + w) * KEYSPACE, ...) — disjoint
# from direct writers (wid * KEYSPACE) and cluster workers (small ints), so
# the getter's structural invariant (value encodes the writer id) holds
GW_KEY_BASE = 500
# every plane journals under one of these commit-user prefixes; the fold is
# ONE fold_landed_rounds call over all of them (str.startswith on a tuple)
MEGA_USER_PREFIXES = ("psoak-w", "cluster-w", GW_USER_PREFIX)

# (process kind, crash spec) pairs: popped per kind at spawn while they
# last, then the seeded random SIGKILL timer takes over. Together the specs
# arm every name in resilience.faults.ALL_CRASH_POINTS (the coverage audit
# test asserts this) across four distinct process kinds.
DEFAULT_MEGA_KILLS = (
    ("writer", "commit:manifests-written:2:kill"),
    ("worker", "cluster:before-ship:2:kill"),
    ("gateway-writer", "gateway:put-sent:2:kill"),
    ("subscriber", "subscriber:batch-journaled:2:kill"),
    ("writer", "commit:snapshot-committed:2:kill"),
    ("worker", "cluster:compact-executing:1:kill"),
    ("writer", "flush:files-written:3:kill"),
    ("writer", "commit:before-manifests:2:kill"),
    ("writer", "flush:before-dispatch:2:kill"),
    # the elastic-topology axis (ISSUE 19): workers dying inside the rescale
    # rewrite window and a retiring worker dying after draining but before
    # its retire RPC — armed on the cluster cells, fired by the elastic
    # churn thread's scripted rescale/admit/retire events
    ("worker", "rescale:files-written:1:kill"),
    ("worker", "rescale:before-ship:1:kill"),
    ("worker", "handoff:before-retire:1:kill"),
)

# metric groups the matrix must tick (the acceptance census)
METRIC_GROUPS = (
    "io", "soak", "get", "sub", "cluster", "sql",
    "gateway", "compaction", "dict", "pallas",
)


# ---------------------------------------------------------------------------
# scenario matrix
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class MegaScenario:
    """One cell of the matrix: a schema shape x bucket mode x CDC wire
    format x engine-toggle combination, with its own process census.
    `table_options` is a tuple of (key, value) pairs (frozen dataclass)."""

    name: str
    schema: str = "kv"  # kv | dict | wide

    @property
    def table_ident(self) -> str:
        """SQL-safe catalog identifier (cell names use hyphens)."""
        return f"mega.{self.name.replace('-', '_')}"

    bucket: int = 4  # -1 = dynamic bucket mode
    cdc_format: str = "debezium-json"
    cluster: bool = False
    direct_writers: int = 1  # proc_soak protocol writers (kv schema only)
    gateway_writers: int = 2
    getters: int = 1
    readers: int = 1
    sql_clients: int = 1
    subscribers: int = 1
    branch_tag: bool = False
    consumer_expiry: bool = False
    table_options: tuple = ()


DEFAULT_MATRIX = (
    # the flagship: every plane at once — cluster mesh ingest + adaptive
    # compaction + direct writers + gateway puts + hedged routed gets +
    # distributed SQL + CDC subscriber + tags/branches
    MegaScenario(
        name="flagship",
        schema="kv",
        bucket=4,
        cdc_format="debezium-json",
        cluster=True,
        direct_writers=1,
        gateway_writers=2,
        branch_tag=True,
    ),
    # dict-string primary key on DYNAMIC buckets, dict-domain merge forced,
    # canal wire format, consumer expiry churn against live heartbeats
    MegaScenario(
        name="dict-dynamic",
        schema="dict",
        bucket=-1,
        cdc_format="canal-json",
        direct_writers=0,
        gateway_writers=2,
        consumer_expiry=True,
        table_options=(("merge.dict-domain", "true"),),
    ),
    # wide mixed schema (float + dict-string + int columns), pallas sort
    # engine, native manifest codec, maxwell wire format
    MegaScenario(
        name="wide-pallas",
        schema="wide",
        bucket=2,
        cdc_format="maxwell-json",
        direct_writers=0,
        gateway_writers=2,
        table_options=(("sort-engine", "pallas"), ("manifest.format", "avro")),
    ),
    # engine-toggle contrast: numpy sort, lane compression off, plain json
    # wire format, cluster plane on a second kv table
    MegaScenario(
        name="native-legacy",
        schema="kv",
        bucket=4,
        cdc_format="json",
        cluster=True,
        direct_writers=1,
        gateway_writers=1,
        table_options=(("sort-engine", "numpy"), ("merge.lane-compression", "false")),
    ),
)


@dataclass
class MegaConfig:
    duration_s: float = 45.0  # per cell
    cluster_workers: int = 2
    seed: int = 0
    scenarios: tuple = DEFAULT_MATRIX
    scripted_kills: tuple = DEFAULT_MEGA_KILLS
    kill_period_s: float = 9.0  # mean seconds between random SIGKILLs (0 = scripted only)
    sweep_period_s: float = 14.0
    sweep_older_than_ms: int = 45_000
    expire_period_s: float = 6.0
    consumer_expire_ms: int = 8_000
    # the composed chaos store: latency shaping + probabilistic faults
    chaos_read_ms: float = 1.0
    chaos_write_ms: float = 0.5
    chaos_possibility: int = 200  # one op in N raises ArtificialException
    chaos_max_fails: int = 1 << 30
    rows_per_commit: int = 200  # direct writers
    gw_rows_per_commit: int = 120  # gateway writers
    round_rows: int = 96  # cluster workers, per owned bucket per round
    table_options: dict = field(default_factory=dict)

    @classmethod
    def from_table_options(cls, options) -> "MegaConfig":
        from ..options import CoreOptions

        o = options.options
        return cls(
            duration_s=o.get(CoreOptions.SOAK_MEGA_DURATION) / 1000.0,
            cluster_workers=o.get(CoreOptions.SOAK_MEGA_CLUSTER_WORKERS),
            kill_period_s=o.get(CoreOptions.SOAK_MEGA_KILL_PERIOD) / 1000.0,
            chaos_read_ms=float(o.get(CoreOptions.SOAK_MEGA_CHAOS_READ)),
            chaos_write_ms=float(o.get(CoreOptions.SOAK_MEGA_CHAOS_WRITE)),
            chaos_possibility=o.get(CoreOptions.SOAK_MEGA_CHAOS_POSSIBILITY),
        )


def scenario_schema(kind: str):
    """The RowType for a matrix schema shape. Key column is always 'k'."""
    from ..types import BIGINT, DOUBLE, STRING, RowType

    if kind == "kv":
        return SCHEMA
    if kind == "dict":
        return RowType.of(("k", STRING()), ("v", STRING()))
    if kind == "wide":
        return RowType.of(
            ("k", BIGINT()), ("v", DOUBLE()), ("tag", STRING()), ("aux", BIGINT())
        )
    raise ValueError(f"unknown mega schema {kind!r}")


# ---------------------------------------------------------------------------
# gateway TCP front door (the wire the client processes speak)
# ---------------------------------------------------------------------------
class GatewayServer:
    """The Gateway as a network service: length-prefixed JSON over TCP (the
    KvQueryServer protocol), methods put / get_batch / sql / slo / ping.
    Typed sheds serialize as {"shed": ShedInfo payload} — the client can
    tell pressure from failure without exception classes on the wire."""

    def __init__(self, gateway, host: str = "127.0.0.1", port: int = 0):
        from .gateway import GatewayShedError

        self.gateway = gateway
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    req = _recv(self.request)
                    if req is None:
                        return
                    rid = req.pop("id", None)
                    method = req.pop("method", "")
                    try:
                        out = outer._dispatch(method, req)
                        out.setdefault("ok", True)
                    except GatewayShedError as e:
                        out = {"ok": False, "shed": e.shed_info.to_payload()}
                    except Exception as e:  # noqa: BLE001 — surface to the client
                        out = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                            "etype": type(e).__name__,
                        }
                    out["id"] = rid
                    try:
                        _send(self.request, out)
                    except OSError:
                        return

        self._server = socketserver.ThreadingTCPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "GatewayServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="mega-gw-server", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def _dispatch(self, method: str, req: dict) -> dict:
        gw = self.gateway
        if method == "ping":
            return {}
        if method == "put":
            sid = gw.put(
                req["rows"],
                kinds=req.get("kinds"),
                tenant=req.get("tenant"),
                user=req.get("user"),
                identifier=req.get("identifier"),
            )
            return {"sid": sid}
        if method == "get_batch":
            keys = [tuple(k) if isinstance(k, list) else k for k in req["keys"]]
            rows = gw.get_batch(keys, tenant=req.get("tenant"))
            return {"rows": [None if r is None else list(r) for r in rows]}
        if method == "sql":
            out = gw.sql(req["stmt"], tenant=req.get("tenant"))
            return {"cols": list(out.schema.field_names), "rows": out.to_pylist()}
        if method == "slo":
            return {"slo": gw.slo()}
        raise ValueError(f"unknown method {method!r}")


class GatewayClient:
    """One dedicated connection to the GatewayServer. `call` returns the
    raw response dict ({"ok": ...} / {"shed": ...} / {"error": ...});
    `retry=True` reconnects once on a connection-grain failure — safe ONLY
    for idempotent reads, never for put (the journal protocol resolves a
    lost put response from the snapshot chain instead)."""

    def __init__(self, host: str, port: int, timeout_s: float = 120.0):
        self.addr = (host, int(port))
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._seq = 0

    def call(self, method: str, retry: bool = True, **kw) -> dict:
        for attempt in (0, 1):
            try:
                if self._sock is None:
                    self._sock = socket.create_connection(self.addr, timeout=self.timeout_s)
                self._seq += 1
                _send(self._sock, {"id": self._seq, "method": method, **kw})
                r = _recv(self._sock)
                if r is None:
                    raise ConnectionError("gateway closed the connection")
                return r
            except (OSError, ConnectionError):
                self.close()
                if not retry or attempt:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


# ---------------------------------------------------------------------------
# child process: gateway writer (journaled puts THROUGH the front door)
# ---------------------------------------------------------------------------
def _gw_fresh_keys(schema: str, wid: int, start: int, n: int) -> list:
    if schema == "dict":
        return [f"gw{wid}-{start + i:08d}" for i in range(n)]
    return [(GW_KEY_BASE + wid) * KEYSPACE + start + i for i in range(n)]


def _gw_value(schema: str, wid: int, ident: int, rng) -> object:
    """Value encodings carry the writer id structurally, so a getter can
    check rows it has no journal for: kv/wide floor(v) % 1000 == wid; dict
    value 'wid:ident:salt' prefixed with the wid."""
    if schema == "dict":
        return f"{wid}:{ident}:{int(rng.integers(0, 1 << 30))}"
    v = float(ident * 1_000.0 + wid + rng.random())
    if schema == "wide":
        return [v, f"t{ident % 5}", int(ident)]
    return v


def _gw_wire_columns(schema: str, rows: dict) -> dict:
    ks = list(rows)
    if schema == "wide":
        vals = [rows[k] for k in ks]
        return {
            "k": ks,
            "v": [r[0] for r in vals],
            "tag": [r[1] for r in vals],
            "aux": [r[2] for r in vals],
        }
    return {"k": ks, "v": [rows[k] for k in ks]}


def gateway_writer_main(args) -> int:
    """Exactly the proc_soak writer protocol — intent fsynced before the
    round, ack after — except the commit happens on the far side of a wire:
    Gateway.put carries (user, identifier) so the snapshot still records
    this writer's identity, and a lost response (connection death, or the
    armed gateway:put-sent crash between the response and the ack) resolves
    from the chain via find_landed_append, adopt-never-replay."""
    from ..resilience.faults import crash_point
    from ..table import load_table
    from .oracle import find_landed_append

    if args.table.startswith(("fail:", "fail-s3", "latency:", "traceable:", "chaos:")):
        from ..fs import testing as _testing  # noqa: F401

    wid = args.wid
    user = f"{GW_USER_PREFIX}{wid}"
    rng = np.random.default_rng(args.seed * 6151 + wid * 104729 + args.incarnation)
    events = WriterJournal.read(args.journal)
    intents = [e for e in events if e["t"] == "intent"]
    resolved = {e["ident"] for e in events if e["t"] in ("ack", "recovered", "abort")}
    acked = {e["ident"] for e in events if e["t"] in ("ack", "recovered")}
    next_ident = max((e["ident"] for e in intents), default=0) + 1
    next_key = max((e["fresh"][0] + e["fresh"][1] for e in intents), default=0)
    decode = str if args.schema == "dict" else int
    landed_keys = [decode(k) for e in intents if e["ident"] in acked for k in e["rows"]]

    # probe-only handle: recovery reads the snapshot chain directly — the
    # gateway may itself be restarting when this incarnation comes up
    table = load_table(args.table, commit_user=user)
    store = table.store
    journal = WriterJournal(args.journal).open()
    recovered = 0
    for e in intents:
        if e["ident"] in resolved:
            continue
        sid = find_landed_append(store, user, e["ident"])
        if sid is not None:
            journal.recovered(e["ident"], sid)
            landed_keys.extend(decode(k) for k in e["rows"])
            recovered += 1
        else:
            journal.abort(e["ident"])
    if recovered:
        print(
            f"gateway writer {wid} incarnation {args.incarnation}: "
            f"recovered {recovered} landed-unacked round(s)",
            flush=True,
        )

    host, port = args.gateway.rsplit(":", 1)
    # a put wedged behind the gateway's put lock (commit-conflict retries
    # under chaos latency) must surface within the drain budget: a timeout
    # is just a lost response, and the chain probe resolves it safely
    client = GatewayClient(host, int(port), timeout_s=30.0)
    rounds = 0
    while rounds < args.max_rounds and not os.path.exists(args.stop_file):
        ident = next_ident
        next_ident += 1
        rounds += 1
        n_upd = int(args.rows_per_commit * args.update_fraction) if landed_keys else 0
        n_new = args.rows_per_commit - n_upd
        fresh = _gw_fresh_keys(args.schema, wid, next_key, n_new)
        upd = (
            [landed_keys[i] for i in rng.integers(0, len(landed_keys), n_upd)]
            if n_upd
            else []
        )
        rows = {k: _gw_value(args.schema, wid, ident, rng) for k in fresh + upd}
        journal.intent(ident, next_key, n_new, rows)
        next_key += n_new
        try:
            r = client.call(
                "put",
                retry=False,  # a lost put resolves via the chain, never a resend
                rows=_gw_wire_columns(args.schema, rows),
                tenant=args.tenant,
                user=user,
                identifier=ident,
            )
        except (ConnectionError, OSError):
            sid = find_landed_append(store, user, ident)
            if sid is not None:
                journal.ack(ident, sid)
                landed_keys.extend(fresh)
            else:
                journal.abort(ident)
            time.sleep(0.2)
            continue
        # the wire exchange completed: this is the landed-but-unacked edge
        # the mega kill schedule arms (gateway:put-sent) — death here leaves
        # the round for the NEXT incarnation's chain probe
        crash_point("gateway:put-sent")
        if r.get("ok"):
            sid = r.get("sid")
            if sid is not None:
                journal.ack(ident, sid)
                landed_keys.extend(fresh)
            else:
                journal.abort(ident)  # nothing committed (empty round)
        elif "shed" in r:
            # typed pressure: verifiably rejected before any byte buffered
            journal.abort(ident)
            time.sleep(max(float(r["shed"].get("retry_after_ms", 25)), 1.0) / 1000.0)
        else:
            # an error crossed the wire (commit conflict give-up, injected
            # fault escaping the retry budget): the chain is the truth
            sid = find_landed_append(store, user, ident)
            if sid is not None:
                journal.ack(ident, sid)
                landed_keys.extend(fresh)
            else:
                journal.abort(ident)
    journal.close()
    client.close()
    return 0


# ---------------------------------------------------------------------------
# child process: getter (point reads through the gateway)
# ---------------------------------------------------------------------------
def _check_row(schema: str, wid: int, row: list) -> bool:
    """Does a returned full row carry writer `wid`'s value encoding?"""
    try:
        if schema == "dict":
            return str(row[1]).split(":", 1)[0] == str(wid)
        return int(float(row[1])) % 1000 == wid
    except (IndexError, TypeError, ValueError):
        return False


def getter_main(args) -> int:
    """Point-gets through the gateway against gateway-writer key ranges,
    asserting the structural value invariant on every non-None row. Typed
    sheds back off; mismatches and unclassified failures are read errors
    (the JSONL log folds through oracle.read_client_logs)."""
    host, port = args.gateway.rsplit(":", 1)
    client = GatewayClient(host, int(port), timeout_s=20.0)
    rng = np.random.default_rng(args.seed * 31 + args.gid * 977 + 5)
    ok = errors = 0
    with open(args.log, "a", buffering=1) as log:
        while not os.path.exists(args.stop_file):
            w = int(rng.integers(0, max(args.gw_writers, 1)))
            offs = rng.integers(0, args.window, 16)
            if args.schema == "dict":
                keys = [f"gw{w}-{int(n):08d}" for n in offs]
            else:
                keys = [int((GW_KEY_BASE + w) * KEYSPACE + n) for n in offs]
            try:
                r = client.call("get_batch", keys=keys, tenant=args.tenant)
            except (ConnectionError, OSError) as exc:
                errors += 1
                log.write(json.dumps({"t": "err", "exc": repr(exc)}) + "\n")
                time.sleep(0.3)
                continue
            if r.get("ok"):
                bad = [
                    row
                    for row in r["rows"]
                    if row is not None and not _check_row(args.schema, w, row)
                ]
                if bad:
                    errors += 1
                    log.write(
                        json.dumps({"t": "err", "kind": "wid-mismatch", "wid": w, "sample": bad[:2]})
                        + "\n"
                    )
                else:
                    ok += 1
            elif "shed" in r:
                time.sleep(max(float(r["shed"].get("retry_after_ms", 25)), 1.0) / 1000.0)
            else:
                errors += 1
                log.write(json.dumps({"t": "err", "exc": r.get("error")}) + "\n")
                time.sleep(0.2)
            time.sleep(0.04)
        log.write(json.dumps({"t": "done", "reads_ok": ok, "read_errors": errors}) + "\n")
    client.close()
    return 0


# ---------------------------------------------------------------------------
# child process: SQL client (aggregates + joins through the gateway)
# ---------------------------------------------------------------------------
def _sql_statements(schema: str, table_ident: str, cluster: bool) -> list[str]:
    stmts = [f"SELECT count(*) FROM {table_ident}"]
    if schema in ("kv", "wide"):
        stmts.append(f"SELECT count(*), sum(v), min(v), max(v) FROM {table_ident}")
    if schema == "wide":
        stmts.append(f"SELECT tag, count(*), sum(aux) FROM {table_ident} GROUP BY tag")
    if cluster and schema == "kv":
        # cluster-worker keys are small ints: the dim table covers them, so
        # the distributed join path returns real matches mid-soak
        stmts.append(
            f"SELECT d.name, count(*) FROM {table_ident} f "
            f"JOIN mega.dim d ON f.k = d.k GROUP BY d.name"
        )
    return stmts


def sql_client_main(args) -> int:
    """Aggregates (and, on cluster cells, distributed JOINs) through the
    gateway's SQL plane while every other plane churns. One in-flight retry
    per statement — a worker respawn surfaces as a typed route shed with a
    backoff, never a client failure."""
    host, port = args.gateway.rsplit(":", 1)
    client = GatewayClient(host, int(port), timeout_s=30.0)
    rng = np.random.default_rng(args.seed * 131 + args.cid * 7 + 11)
    stmts = _sql_statements(args.schema, args.ident, args.cluster)
    ok = errors = 0
    with open(args.log, "a", buffering=1) as log:
        while not os.path.exists(args.stop_file):
            stmt = stmts[int(rng.integers(0, len(stmts)))]
            failed = None
            for _ in range(3):
                try:
                    r = client.call("sql", stmt=stmt, tenant=args.tenant)
                except (ConnectionError, OSError) as exc:
                    failed = repr(exc)
                    time.sleep(0.3)
                    continue
                if r.get("ok"):
                    failed = None
                    ok += 1
                    break
                if "shed" in r:
                    failed = "shed"
                    time.sleep(
                        max(float(r["shed"].get("retry_after_ms", 25)), 1.0) / 1000.0
                    )
                    continue
                failed = r.get("error")
                time.sleep(0.2)
            if failed is not None and failed != "shed":
                errors += 1
                log.write(json.dumps({"t": "err", "stmt": stmt, "exc": failed}) + "\n")
            time.sleep(0.15)
        log.write(json.dumps({"t": "done", "reads_ok": ok, "read_errors": errors}) + "\n")
    client.close()
    return 0


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------
class MegaSoakSupervisor:
    """One warehouse, one chaos store, every plane, one verdict."""

    def __init__(self, base_dir: str, cfg: "MegaConfig | None" = None):
        from ..fs.testing import CHAOS_ENV, chaos_spec  # registers chaos://

        self.cfg = cfg or MegaConfig()
        self.base_dir = str(base_dir)
        self.warehouse_posix = os.path.join(self.base_dir, "warehouse")
        self.run_root = os.path.join(self.base_dir, "mega_run")
        self.domain = f"mega{self.cfg.seed}"
        self.chaos_env_key = CHAOS_ENV
        self.chaos_value = chaos_spec(
            self.domain,
            read_ms=self.cfg.chaos_read_ms,
            write_ms=self.cfg.chaos_write_ms,
            possibility=self.cfg.chaos_possibility,
            max_fails=self.cfg.chaos_max_fails,
            seed=self.cfg.seed,
        )
        self.warehouse = f"chaos://{self.domain}{os.path.abspath(self.warehouse_posix)}"
        self.cells: list[dict] = []
        self.counts = {
            "procs_spawned": 0,
            "procs_killed": 0,
            "procs_respawned": 0,
            "child_errors": 0,
            "sweeps_during_soak": 0,
            "snapshot_expiries": 0,
            "faults_injected": 0,
            "rescales_requested": 0,
            "workers_admitted": 0,
            "workers_retired": 0,
        }
        self.kills_by_kind: dict[str, int] = {}
        self.kills_by_point: dict[str, int] = {}

    # ---- chaos lifecycle ----------------------------------------------
    def _arm_chaos(self) -> None:
        from ..fs.testing import apply_chaos_env

        os.environ[self.chaos_env_key] = self.chaos_value
        apply_chaos_env(self.chaos_value)

    def _heal_chaos(self) -> None:
        """Verification runs on the healed store: drop latency shaping and
        the fault domain (chaos:// then degrades to plain local IO), after
        banking the injected-fault count."""
        from ..fs.testing import FailingFileIO, LatencyFileIO

        self.counts["faults_injected"] += FailingFileIO.fails_injected(self.domain)
        os.environ.pop(self.chaos_env_key, None)
        FailingFileIO._states.pop(self.domain, None)
        LatencyFileIO.configure(read_ms=0.0, write_ms=0.0)

    # ---- table/catalog setup ------------------------------------------
    def _catalog(self):
        from ..catalog import FileSystemCatalog

        return FileSystemCatalog(self.warehouse, commit_user="mega-supervisor")

    def _cell_table_options(self, sc: MegaScenario) -> dict:
        cfg = self.cfg
        opts = {
            "bucket": str(sc.bucket),
            "write-buffer-rows": "256",
            # the resilience budget that turns chaos faults into retries —
            # without it an ArtificialException (an IOError) would escape a
            # gateway put as an UNTYPED shed and fail the acceptance gate
            "commit.max-retries": "30",
            "commit.retry-backoff": "2 ms",
            "fs.retry.max-attempts": "6",
            "fs.retry.initial-backoff": "2 ms",
            "fs.retry.max-backoff": "40 ms",
            "snapshot.num-retained.min": "16",
            "snapshot.num-retained.max": "30",
            "subscription.queue-depth": "4",
            "subscription.heartbeat-interval": "1 s",
            "subscription.poll-backoff": "20 ms",
            # three tenants with distinct weights: ingest > serve > analytics
            "gateway.tenant.ingest.weight": "3.0",
            "gateway.tenant.ingest.max-inflight": "8",
            "gateway.tenant.serve.weight": "2.0",
            "gateway.tenant.serve.max-inflight": "8",
            "gateway.tenant.analytics.weight": "1.0",
            "gateway.tenant.analytics.max-inflight": "4",
            "gateway.hedge.enabled": "true",
            "gateway.hedge.deadline-ms": "60",
            "gateway.hedge.max-fraction": "0.5",
        }
        if sc.cluster:
            opts.update(
                {
                    "write-only": "true",  # compaction belongs to the coordinator drain
                    "merge.engine": "mesh",
                    "cluster.workers": str(cfg.cluster_workers),
                    "compaction.adaptive.read-amp-ceiling": "12",
                    "compaction.adaptive.interval": "300 ms",
                    "compaction.adaptive.max-buckets-per-round": "2",
                }
            )
        opts.update(dict(sc.table_options))
        opts.update(cfg.table_options)
        return opts

    def _ensure_dim_table(self, catalog) -> None:
        """The static join dimension (k BIGINT, name STRING): keys 0..4095
        cover the cluster workers' small-int key pools, so mid-soak
        distributed JOINs return real matches."""
        from ..core.manifest import ManifestCommittable
        from ..data.batch import ColumnBatch
        from ..table.write import TableWrite
        from ..types import BIGINT, STRING, RowType

        dim_type = RowType.of(("k", BIGINT()), ("name", STRING()))
        table = catalog.create_table(
            "mega.dim",
            dim_type,
            primary_keys=["k"],
            options={"bucket": "2", "fs.retry.max-attempts": "6"},
            ignore_if_exists=True,
        )
        if table.store.snapshot_manager.latest_snapshot_id() is not None:
            return
        ks = list(range(4096))
        tw = TableWrite(table)
        try:
            tw.write(
                ColumnBatch.from_pydict(
                    dim_type, {"k": ks, "name": [f"n{k % 7}" for k in ks]}
                )
            )
            msgs = tw.prepare_commit()
        finally:
            tw.close()
        table.store.new_commit().commit(ManifestCommittable(1, messages=msgs))

    # ---- child process plumbing ---------------------------------------
    def _child_env(self, crash_spec: "str | None", role: "str | None" = None,
                   devices: int = 0) -> dict:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PAIMON_TPU_CRASH_POINT", None)
        env.pop("PAIMON_TPU_CLUSTER_ROLE", None)
        if crash_spec:
            env["PAIMON_TPU_CRASH_POINT"] = crash_spec
        if role:
            env["PAIMON_TPU_CLUSTER_ROLE"] = role
        if devices:
            flags = " ".join(
                f
                for f in env.get("XLA_FLAGS", "").split()
                if not f.startswith("--xla_force_host_platform_device_count")
            )
            env["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={devices}"
            ).strip()
        repo_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""
        )
        return env

    def _next_spec(self, kind: str) -> "str | None":
        queue = self._spec_queues.get(kind)
        return queue.pop(0) if queue else None

    def _spawn(self, cell, kind: str, idx: int, cmd: list, *, crash_armed: bool,
               role: "str | None" = None, devices: int = 0) -> None:
        from ..metrics import soak_metrics

        spec = self._next_spec(kind) if crash_armed else None
        inc = self._incarnations.get((kind, idx), 0)
        self._incarnations[(kind, idx)] = inc + 1
        log = open(os.path.join(cell["run_dir"], f"{kind}-{idx}.{inc}.log"), "wb")
        p = subprocess.Popen(
            cmd,
            stdout=log,
            stderr=subprocess.STDOUT,
            env=self._child_env(spec, role=role, devices=devices),
        )
        log.close()
        self._procs[(kind, idx)] = (p, spec)
        self.counts["procs_spawned"] += 1
        soak_metrics().counter("procs_spawned").inc()

    def _spawn_child(self, cell, kind: str, idx: int) -> None:
        """(Re)spawn one child of `kind` for this cell — the factory the
        kill/respawn loop calls, so every respawn re-arms from the same
        per-kind crash-spec queues."""
        sc: MegaScenario = cell["scenario"]
        cfg = self.cfg
        run_dir = cell["run_dir"]
        table_uri = cell["table_uri"]
        if kind == "writer":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.proc_soak", "writer",
                "--table", table_uri,
                "--wid", str(idx),
                "--journal", os.path.join(run_dir, f"direct-journal-{idx}.jsonl"),
                "--stop-file", cell["stop_file"],
                "--seed", str(cfg.seed),
                "--incarnation", str(self._incarnations.get((kind, idx), 0)),
                "--rows-per-commit", str(cfg.rows_per_commit),
                "--chunk-rows", "100",
                "--update-fraction", "0.3",
                # a write-only cluster table refuses writer-side compaction
                "--compact-every", "0" if sc.cluster else "5",
                "--max-memory", str(256 * 1024),
                "--block-timeout-ms", "20000",
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=True)
        elif kind == "worker":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.cluster", "worker",
                "--table", table_uri,
                "--wid", str(idx),
                "--coordinator", f"{cell['coordinator'].host}:{cell['coordinator'].port}",
                "--journal", os.path.join(run_dir, f"cluster-journal-{idx}.jsonl"),
                "--incarnation", str(self._incarnations.get((kind, idx), 0)),
                "--seed", str(cfg.seed),
                "--round-rows", str(cfg.round_rows),
                "--devices", "2",
                "--admit-timeout", "30.0",
                "--heartbeat-interval", "0.5",
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=True, role="worker", devices=2)
        elif kind == "gateway-writer":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.mega_soak", "gateway-writer",
                "--table", table_uri,
                "--gateway", f"{cell['server'].host}:{cell['server'].port}",
                "--wid", str(idx),
                "--schema", sc.schema,
                "--journal", os.path.join(run_dir, f"gw-journal-{idx}.jsonl"),
                "--stop-file", cell["stop_file"],
                "--seed", str(cfg.seed),
                "--incarnation", str(self._incarnations.get((kind, idx), 0)),
                "--rows-per-commit", str(cfg.gw_rows_per_commit),
                "--tenant", "ingest",
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=True)
        elif kind == "subscriber":
            remaining = max(cell["deadline"] - time.monotonic(), 1.0)
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.subscription",
                "--table", table_uri,
                "--consumer", f"mega-sub-{idx}",
                "--journal", os.path.join(run_dir, f"sub-{idx}.jsonl"),
                "--duration", str(remaining + 5.0),
                "--from-snapshot", "1",
                "--format", sc.cdc_format,
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=True)
        elif kind == "getter":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.mega_soak", "getter",
                "--gateway", f"{cell['server'].host}:{cell['server'].port}",
                "--gid", str(idx),
                "--schema", sc.schema,
                "--gw-writers", str(sc.gateway_writers),
                "--log", os.path.join(run_dir, f"gets-{idx}.jsonl"),
                "--stop-file", cell["stop_file"],
                "--seed", str(cfg.seed),
                "--tenant", "serve",
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=False)
        elif kind == "sql-client":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.mega_soak", "sql-client",
                "--gateway", f"{cell['server'].host}:{cell['server'].port}",
                "--cid", str(idx),
                "--schema", sc.schema,
                "--ident", sc.table_ident,
                "--log", os.path.join(run_dir, f"sql-{idx}.jsonl"),
                "--stop-file", cell["stop_file"],
                "--seed", str(cfg.seed),
                "--tenant", "analytics",
            ] + (["--cluster"] if sc.cluster else [])
            self._spawn(cell, kind, idx, cmd, crash_armed=False)
        elif kind == "reader":
            cmd = [
                sys.executable, "-m", "paimon_tpu.service.proc_soak", "reader",
                "--table", table_uri,
                "--rid", str(idx),
                "--log", os.path.join(run_dir, f"reads-{idx}.jsonl"),
                "--stop-file", cell["stop_file"],
            ]
            self._spawn(cell, kind, idx, cmd, crash_armed=False)
        else:
            raise ValueError(f"unknown child kind {kind!r}")

    def _reap(self, cell, kind: str, idx: int, rc: int, spec: "str | None") -> None:
        from ..metrics import soak_metrics
        from ..resilience.faults import KILL_EXIT_CODE, _parse_spec

        if rc == KILL_EXIT_CODE or rc < 0:
            self.counts["procs_killed"] += 1
            self.kills_by_kind[kind] = self.kills_by_kind.get(kind, 0) + 1
            # rc == 137 is os._exit at an ARMED point; rc < 0 is the seeded
            # random SIGKILL (Popen reports the signal as a negative rc)
            point = _parse_spec(spec)[0] if (spec and rc == KILL_EXIT_CODE) else "random-sigkill"
            self.kills_by_point[point] = self.kills_by_point.get(point, 0) + 1
            soak_metrics().counter("procs_killed").inc()
        elif rc != 0:
            self.counts["child_errors"] += 1
            inc = self._incarnations.get((kind, idx), 1) - 1
            log = os.path.join(cell["run_dir"], f"{kind}-{idx}.{inc}.log")
            tail = ""
            if os.path.exists(log):
                with open(log, "rb") as f:
                    tail = f.read()[-2000:].decode(errors="replace")
            cell["errors"].append(f"{kind} {idx} exited rc={rc}:\n{tail}")

    # ---- churn threads -------------------------------------------------
    def _churn_loop(self, cell, deadline: float) -> None:
        """Snapshot expiry + orphan sweep + consumer expiry + tag/branch
        creation, all racing the write/read/subscribe planes."""
        from ..resilience.orphan import remove_orphan_files
        from ..table import load_table
        from ..table.consumer import ConsumerManager

        sc: MegaScenario = cell["scenario"]
        cfg = self.cfg
        table = load_table(cell["table_uri"], commit_user="mega-churn")

        _FAILED = object()  # hard failure (recorded) vs None = IO fault, retry

        def churn_try(label: str, fn):
            """Background churn under live chaos: an IOError here IS the
            fault injector working (the next period retries); anything else
            is a real defect and fails the cell."""
            try:
                return fn()
            except IOError:
                cell["churn_io_faults"] = cell.get("churn_io_faults", 0) + 1
                return None
            except Exception:
                cell["errors"].append(f"{label} crashed:\n{traceback.format_exc()}")
                return _FAILED

        next_expire = time.monotonic() + cfg.expire_period_s
        next_sweep = time.monotonic() + cfg.sweep_period_s
        next_consumer = time.monotonic() + cfg.expire_period_s
        tag_at = time.monotonic() + 0.4 * cfg.duration_s if sc.branch_tag else float("inf")
        branch_at = time.monotonic() + 0.6 * cfg.duration_s if sc.branch_tag else float("inf")
        if sc.consumer_expiry:
            # the decoy: a consumer nobody heartbeats, destined to expire
            # while the live subscribers' beats keep theirs fresh
            ConsumerManager(table.file_io, table.path).record("mega-dead", 1)
        while time.monotonic() < deadline and not cell["stop"].is_set():
            now = time.monotonic()
            if now >= next_expire:
                r = churn_try("snapshot expiry", table.expire_snapshots)
                if r is not None and r is not _FAILED:
                    self.counts["snapshot_expiries"] += 1
                next_expire = now + cfg.expire_period_s
            if now >= next_sweep:
                r = churn_try(
                    "mid-soak sweep",
                    lambda: remove_orphan_files(
                        table, older_than_millis=cfg.sweep_older_than_ms
                    ),
                )
                if r is not None and r is not _FAILED:
                    self.counts["sweeps_during_soak"] += 1
                next_sweep = now + cfg.sweep_period_s
            if sc.consumer_expiry and now >= next_consumer:
                expired = churn_try(
                    "consumer expiry",
                    lambda: ConsumerManager(table.file_io, table.path).expire_stale(
                        cfg.consumer_expire_ms
                    ),
                )
                if expired is not None and expired is not _FAILED:
                    cell["expired_consumers"].update(expired)
                next_consumer = now + cfg.expire_period_s
            if now >= tag_at:
                from ..sql import call as sql_call

                done = churn_try(
                    "create_tag",
                    lambda: sql_call(
                        cell["catalog"], f"CALL sys.create_tag('{sc.table_ident}', 'mega-v1')"
                    ),
                )
                if done is not None:  # landed or hard-failed; an IO fault retries
                    tag_at = float("inf")
                if done is not None and done is not _FAILED:
                    cell["tagged"] = True
            if now >= branch_at and cell.get("tagged"):
                from ..sql import call as sql_call

                done = churn_try(
                    "create_branch",
                    lambda: sql_call(
                        cell["catalog"],
                        f"CALL sys.create_branch('{sc.table_ident}', 'exp', 'mega-v1')",
                    ),
                )
                if done is not None:
                    branch_at = float("inf")
                if done is not None and done is not _FAILED:
                    cell["branched"] = True
            time.sleep(0.2)

    def _gw_subscriber_loop(self, cell, deadline: float) -> None:
        """An in-process subscriber THROUGH the gateway: exercises the
        gateway subscribe plane (and the sub{...} metric group) beside the
        journaled subscriber OS processes."""
        from .gateway import GatewayShedError

        gw = cell["gateway"]
        sub_id = None
        rows = 0
        while time.monotonic() < deadline and not cell["stop"].is_set():
            try:
                if sub_id is None:
                    sub_id = gw.subscribe_open(
                        consumer_id="mega-gwsub", from_snapshot=1, tenant="serve"
                    )
                out = gw.subscribe_poll(sub_id, timeout_ms=500, tenant="serve")
                rows += len(out.get("rows", ()))
            except GatewayShedError as e:
                sub_id = None
                time.sleep(max(int(e.shed_info.retry_after_ms or 25), 1) / 1000.0)
            except Exception:
                sub_id = None
                time.sleep(0.3)
        cell["gw_sub_rows"] = rows
        if sub_id is not None:
            try:
                gw.subscribe_close(sub_id)
            except Exception:
                pass

    def _elastic_loop(self, cell, t_start: float, deadline: float) -> None:
        """The elastic-topology axis on cluster cells: one live rescale
        under the full chaos load, one worker admit (the join-steal range
        handoff), one planned retire (drain + handoff) — scripted at fixed
        fractions of the cell duration so the armed rescale:*/handoff:*
        crash specs have a live window to fire in."""
        sc: MegaScenario = cell["scenario"]
        coord = cell["coordinator"]
        dur = max(deadline - t_start, 1.0)
        plan = []
        if sc.bucket > 0:  # dynamic tables assign buckets per key
            plan.append((t_start + 0.35 * dur, "rescale"))
        plan.append((t_start + 0.55 * dur, "admit"))
        plan.append((t_start + 0.75 * dur, "retire"))
        while plan and time.monotonic() < deadline and not cell["stop"].is_set():
            if time.monotonic() < plan[0][0]:
                time.sleep(0.2)
                continue
            _, act = plan.pop(0)
            try:
                if act == "rescale":
                    r = coord.start_rescale(coord.num_buckets * 2)
                    if r.get("started"):
                        self.counts["rescales_requested"] += 1
                elif act == "admit":
                    idx = 1 + max(
                        (i for k, i in self._procs if k == "worker"), default=-1
                    )
                    self._spawn_child(cell, "worker", idx)
                    self.counts["workers_admitted"] += 1
                elif act == "retire":
                    live = sorted(
                        i
                        for (k, i), (p, _) in list(self._procs.items())
                        if k == "worker"
                        and p.poll() is None
                        and ("worker", i) not in cell["no_respawn"]
                    )
                    if len(live) > 1:  # never retire the last worker
                        wid = live[-1]  # the admitted joiner when present
                        cell["no_respawn"].add(("worker", wid))
                        coord.request_retire(wid)
                        self.counts["workers_retired"] += 1
            except Exception:
                cell["errors"].append(f"elastic {act} failed:\n{traceback.format_exc()}")

    # ---- one cell ------------------------------------------------------
    def _census(self, sc: MegaScenario) -> dict[str, int]:
        counts = {
            "writer": sc.direct_writers if sc.schema == "kv" else 0,
            "worker": self.cfg.cluster_workers if sc.cluster else 0,
            "gateway-writer": sc.gateway_writers,
            "subscriber": sc.subscribers,
            "getter": sc.getters,
            "sql-client": sc.sql_clients,
            "reader": sc.readers,
        }
        return {k: v for k, v in counts.items() if v > 0}

    def run_cell(self, sc: MegaScenario) -> dict:
        from ..metrics import gateway_metrics
        from .cluster import ClusterClient, ClusterConfig, ClusterCoordinator
        from .gateway import Gateway

        cfg = self.cfg
        # the untyped-shed gate is a per-cell DELTA of the process-global
        # counter — bank the baseline before any gateway traffic
        untyped_at_start = gateway_metrics().counter("sheds_untyped").count
        run_dir = os.path.join(self.run_root, sc.name)
        os.makedirs(run_dir, exist_ok=True)
        self._arm_chaos()
        catalog = self._catalog()
        schema = scenario_schema(sc.schema)
        catalog.create_table(
            sc.table_ident,
            schema,
            primary_keys=["k"],
            options=self._cell_table_options(sc),
            ignore_if_exists=True,
        )
        if sc.schema == "kv":
            self._ensure_dim_table(catalog)
        table = catalog.get_table(sc.table_ident)
        cell: dict = {
            "scenario": sc,
            "run_dir": run_dir,
            "table_uri": catalog.table_path(sc.table_ident),
            "stop_file": os.path.join(run_dir, "stop"),
            "stop": threading.Event(),
            "catalog": catalog,
            "errors": [],
            "inconsistencies": [],
            "expired_consumers": set(),
            "untyped_at_start": untyped_at_start,
            "no_respawn": set(),  # retired workers stay retired
        }
        self._procs: dict[tuple, tuple] = {}
        self._incarnations: dict[tuple, int] = {}
        # fresh per-kind crash-spec queues: every cell re-covers the points
        # its process census can fire
        census = self._census(sc)
        self._spec_queues = {}
        for kind, spec in cfg.scripted_kills:
            if kind in census:
                self._spec_queues.setdefault(kind, []).append(spec)

        coordinator = client = None
        if sc.cluster:
            ccfg = ClusterConfig(
                workers=cfg.cluster_workers,
                buckets=max(sc.bucket, 1),
                round_rows=cfg.round_rows,
                compaction=True,
                serve=True,
                seed=cfg.seed,
            )
            coordinator = ClusterCoordinator(cell["table_uri"], ccfg).start()
            coordinator.go_event.set()
            client = ClusterClient(table, coordinator.host, coordinator.port)
        cell["coordinator"] = coordinator
        gateway = Gateway(table, catalog=catalog, client=client)
        server = GatewayServer(gateway).start()
        cell["gateway"], cell["server"] = gateway, server

        rng = np.random.default_rng(cfg.seed * 31 + 17 + len(self.cells))
        t_start = time.monotonic()
        deadline = t_start + cfg.duration_s
        cell["deadline"] = deadline
        for kind, n in census.items():
            for i in range(n):
                self._spawn_child(cell, kind, i)
        threads = [
            threading.Thread(
                target=self._churn_loop, args=(cell, deadline), name="mega-churn", daemon=True
            ),
            threading.Thread(
                target=self._gw_subscriber_loop,
                args=(cell, deadline),
                name="mega-gwsub",
                daemon=True,
            ),
        ]
        if sc.cluster:
            threads.append(
                threading.Thread(
                    target=self._elastic_loop,
                    args=(cell, t_start, deadline),
                    name="mega-elastic",
                    daemon=True,
                )
            )
        for t in threads:
            t.start()

        killable = [k for k in ("writer", "worker", "gateway-writer", "subscriber") if k in census]
        next_kill = (
            t_start + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
            if (cfg.kill_period_s > 0 and killable)
            else float("inf")
        )
        try:
            while time.monotonic() < deadline:
                for (kind, idx), (p, spec) in list(self._procs.items()):
                    rc = p.poll()
                    if rc is None:
                        continue
                    self._reap(cell, kind, idx, rc, spec)
                    if (kind, idx) in cell["no_respawn"]:
                        del self._procs[(kind, idx)]  # planned retire: gone for good
                        continue
                    self._spawn_child(cell, kind, idx)
                    self.counts["procs_respawned"] += 1
                now = time.monotonic()
                if now >= next_kill:
                    kind = killable[int(rng.integers(0, len(killable)))]
                    idx = int(rng.integers(0, census[kind]))
                    victim = self._procs.get((kind, idx))
                    if victim is not None and victim[0].poll() is None:
                        victim[0].kill()  # SIGKILL: reaped (and counted) next loop
                    next_kill = now + float(rng.uniform(0.5, 1.5)) * cfg.kill_period_s
                time.sleep(0.15)
        finally:
            # ---- drain -------------------------------------------------
            cell["stop"].set()
            with open(cell["stop_file"], "w") as f:
                f.write("stop")
            if coordinator is not None:
                coordinator.stop_event.set()
            drain_deadline = time.monotonic() + 90.0
            for (kind, idx), (p, spec) in list(self._procs.items()):
                timeout = max(1.0, drain_deadline - time.monotonic())
                try:
                    rc = p.wait(timeout=timeout)
                    if rc not in (0, None):
                        self._reap(cell, kind, idx, rc, spec)
                except subprocess.TimeoutExpired:
                    cell["errors"].append(f"{kind} {idx} failed to drain; killed")
                    p.kill()
                    p.wait(timeout=30)
            for t in threads:
                t.join(timeout=15)
            gateway.close()
            server.close()
            if client is not None:
                client.close()
            if coordinator is not None:
                coordinator.close()
        wall_s = time.monotonic() - t_start
        self._heal_chaos()
        report = self._verify_cell(cell, wall_s)
        self.cells.append(report)
        return report

    # ---- per-cell verification ----------------------------------------
    def _journals(self, cell) -> dict[str, str]:
        sc: MegaScenario = cell["scenario"]
        run_dir = cell["run_dir"]
        journals: dict[str, str] = {}
        if sc.schema == "kv":
            for w in range(sc.direct_writers):
                journals[f"psoak-w{w}"] = os.path.join(run_dir, f"direct-journal-{w}.jsonl")
        if sc.cluster:
            for w in range(self.cfg.cluster_workers):
                journals[f"cluster-w{w}"] = os.path.join(run_dir, f"cluster-journal-{w}.jsonl")
        for w in range(sc.gateway_writers):
            journals[f"{GW_USER_PREFIX}{w}"] = os.path.join(run_dir, f"gw-journal-{w}.jsonl")
        return journals

    def _verify_subscribers(self, cell, table) -> dict:
        """Each subscriber journal (CDC-format round-tripped rows) folds to
        exactly the pinned scan at its checkpoint — across kill -9s and
        at-least-once replays (sid-keyed overwrite)."""
        from ..types import RowKind

        sc: MegaScenario = cell["scenario"]
        out = {"sub_batches": 0, "sub_mismatches": 0, "sub_journals": 0}
        for i in range(sc.subscribers):
            path = os.path.join(cell["run_dir"], f"sub-{i}.jsonl")
            events = WriterJournal.read(path)
            by_sid: dict[int, tuple] = {}
            for rec in events:
                if "sid" in rec:
                    by_sid[rec["sid"]] = (rec["rows"], rec["kinds"])
            if not by_sid:
                cell["errors"].append(f"subscriber {i} journal recorded no batches")
                continue
            out["sub_journals"] += 1
            out["sub_batches"] += len(by_sid)
            checkpoint = max(by_sid)
            state: dict = {}
            for sid in sorted(by_sid):
                rows, kinds = by_sid[sid]
                for row, kind in zip(rows, kinds):
                    k = RowKind(int(kind))
                    if k in (RowKind.INSERT, RowKind.UPDATE_AFTER):
                        state[row[0]] = tuple(row)
                    elif k == RowKind.DELETE:
                        state.pop(row[0], None)
            try:
                pinned = table.copy({"scan.snapshot-id": str(checkpoint)})
                rb = pinned.new_read_builder()
                batch = rb.new_read().read_all(rb.new_scan().plan())
                expected = {row[0]: tuple(row) for row in batch.to_pylist()}
            except Exception:
                cell["errors"].append(
                    f"subscriber {i} pinned scan @{checkpoint} crashed:\n{traceback.format_exc()}"
                )
                continue
            if state != expected:
                out["sub_mismatches"] += 1
                missing = [k for k in expected if k not in state]
                extra = [k for k in state if k not in expected]
                cell["inconsistencies"].append(
                    {
                        "kind": "sub-journal-mismatch",
                        "subscriber": i,
                        "checkpoint": checkpoint,
                        "missing": len(missing),
                        "extra": len(extra),
                        "sample": (missing[:3], extra[:3]),
                    }
                )
        return out

    def _sql_battery(self, cell, table, expected: dict) -> dict:
        """Quiesced, healed-store SQL gate: every statement runs twice
        through a (local-route) gateway and once through sql.select.query —
        the three answers must be BIT-IDENTICAL — and count(*) must equal
        the fold's unique-key count."""
        from ..sql.select import query
        from .gateway import Gateway

        sc: MegaScenario = cell["scenario"]
        mismatches = 0
        stmts = _sql_statements(sc.schema, sc.table_ident, cluster=False)
        gw = Gateway(table, catalog=cell["catalog"])
        try:
            for stmt in stmts:
                try:
                    a = gw.sql(stmt, tenant="analytics").to_pylist()
                    b = gw.sql(stmt, tenant="analytics").to_pylist()
                    c = query(cell["catalog"], stmt).to_pylist()
                except Exception:
                    cell["errors"].append(
                        f"sql battery crashed on {stmt!r}:\n{traceback.format_exc()}"
                    )
                    continue
                if not (a == b == c):
                    mismatches += 1
                    cell["inconsistencies"].append(
                        {"kind": "sql-battery-mismatch", "stmt": stmt, "gw": a[:2], "local": c[:2]}
                    )
            try:
                n = query(cell["catalog"], f"SELECT count(*) FROM {sc.table_ident}").to_pylist()
                if int(n[0][0]) != len(expected):
                    mismatches += 1
                    cell["inconsistencies"].append(
                        {
                            "kind": "sql-count-vs-fold",
                            "sql": int(n[0][0]),
                            "fold": len(expected),
                        }
                    )
            except Exception:
                cell["errors"].append(f"sql count check crashed:\n{traceback.format_exc()}")
        finally:
            gw.close()
        return {"sql_battery_stmts": len(stmts), "sql_battery_mismatches": mismatches}

    def _verify_tag_branch(self, cell, table, landed: dict) -> dict:
        """Time travel agrees with history: the scan at the tag's snapshot
        (direct, SQL `FOR VERSION AS OF`, and the branch forked from the
        tag) equals the fold of landed rounds up to that snapshot."""
        from ..sql.select import query
        from ..table import load_table
        from .oracle import scan_rows

        sc: MegaScenario = cell["scenario"]
        out = {"tag_sid": None, "tag_mismatches": 0, "branch_rows": None}
        if not cell.get("tagged"):
            cell["errors"].append("branch_tag cell never created its tag")
            return out
        tags = table.tags()
        if "mega-v1" not in tags:
            cell["errors"].append(f"tag mega-v1 missing (tags: {sorted(tags)})")
            return out
        tag_sid = tags["mega-v1"]
        out["tag_sid"] = tag_sid
        expected_at_tag: dict = {}
        for sid in sorted(landed):
            if sid <= tag_sid:
                expected_at_tag.update(landed[sid])
        try:
            got, _physical = scan_rows(table, tag_sid)
        except Exception:
            cell["errors"].append(f"tag scan crashed:\n{traceback.format_exc()}")
            return out
        if got != expected_at_tag:
            out["tag_mismatches"] += 1
            cell["inconsistencies"].append(
                {
                    "kind": "tag-scan-vs-fold",
                    "tag_sid": tag_sid,
                    "scan": len(got),
                    "fold": len(expected_at_tag),
                }
            )
        try:
            n = query(
                cell["catalog"],
                f"SELECT count(*) FROM {sc.table_ident} FOR VERSION AS OF 'mega-v1'",
            ).to_pylist()
            if int(n[0][0]) != len(expected_at_tag):
                out["tag_mismatches"] += 1
                cell["inconsistencies"].append(
                    {"kind": "time-travel-count", "sql": int(n[0][0]), "fold": len(expected_at_tag)}
                )
        except Exception:
            cell["errors"].append(f"time-travel SQL crashed:\n{traceback.format_exc()}")
        if cell.get("branched"):
            try:
                bt = load_table(
                    cell["table_uri"], commit_user="mega-verify", dynamic_options={"branch": "exp"}
                )
                bgot, _ = scan_rows(bt, bt.store.snapshot_manager.latest_snapshot_id())
                out["branch_rows"] = len(bgot)
                if bgot != expected_at_tag:
                    out["tag_mismatches"] += 1
                    cell["inconsistencies"].append(
                        {
                            "kind": "branch-scan-vs-fold",
                            "branch": len(bgot),
                            "fold": len(expected_at_tag),
                        }
                    )
            except Exception:
                cell["errors"].append(f"branch scan crashed:\n{traceback.format_exc()}")
        return out

    def _verify_consumer_expiry(self, cell, table) -> dict:
        from ..table.consumer import ConsumerManager

        sc: MegaScenario = cell["scenario"]
        out = {"expired_consumers": sorted(cell["expired_consumers"])}
        if not sc.consumer_expiry:
            return out
        live = ConsumerManager(table.file_io, table.path).list_consumers()
        if "mega-dead" not in cell["expired_consumers"]:
            cell["inconsistencies"].append(
                {"kind": "decoy-consumer-survived", "live": sorted(live)}
            )
        # a heartbeating subscriber must never be reaped by the expiry churn
        reaped_live = [
            c for c in cell["expired_consumers"] if c.startswith("mega-sub-")
        ]
        if reaped_live:
            cell["inconsistencies"].append(
                {"kind": "live-consumer-expired", "consumers": reaped_live}
            )
        return out

    def _verify_cell(self, cell, wall_s: float) -> dict:
        from ..metrics import gateway_metrics
        from ..table import load_table
        from .oracle import fold_landed_rounds, read_client_logs, verify_table_state

        sc: MegaScenario = cell["scenario"]
        run_dir = cell["run_dir"]
        table = load_table(cell["table_uri"], commit_user="mega-verify")
        untyped_before = cell.get("untyped_at_start", 0)
        decode = str if sc.schema == "dict" else int
        landed, stats = fold_landed_rounds(
            table.store,
            self._journals(cell),
            user_prefix=MEGA_USER_PREFIXES,
            inconsistencies=cell["inconsistencies"],
            decode_key=decode,
        )
        if sc.schema == "wide":
            # journal values are JSON lists; the scan yields tuples
            landed = {
                sid: {k: tuple(v) if isinstance(v, list) else v for k, v in rows.items()}
                for sid, rows in landed.items()
            }
        expected: dict = {}
        for sid in sorted(landed):
            expected.update(landed[sid])
        if stats["double_applied"]:
            cell["inconsistencies"].append(
                {"kind": "double-applied", "rounds": stats["double_applied"]}
            )
        # subscriber folds FIRST: their pinned checkpoints predate the
        # verification compaction's extra snapshots
        subs = self._verify_subscribers(cell, table)
        state = verify_table_state(
            table,
            expected,
            os.path.join(self.warehouse_posix, "mega.db", sc.table_ident.split(".", 1)[1]),
            cell["errors"],
            cell["inconsistencies"],
            sweep=True,
            force_writable=sc.cluster,
        )
        sql = self._sql_battery(cell, table, expected)
        tag = self._verify_tag_branch(cell, table, landed) if sc.branch_tag else {}
        consumers = self._verify_consumer_expiry(cell, table)
        reads = read_client_logs(
            [os.path.join(run_dir, f"reads-{r}.jsonl") for r in range(sc.readers)]
        )
        gets = read_client_logs(
            [os.path.join(run_dir, f"gets-{g}.jsonl") for g in range(sc.getters)]
        )
        sqlc = read_client_logs(
            [os.path.join(run_dir, f"sql-{c}.jsonl") for c in range(sc.sql_clients)]
        )
        untyped = gateway_metrics().counter("sheds_untyped").count - untyped_before
        consistent = (
            not cell["errors"]
            and not cell["inconsistencies"]
            and state["lost_rows"] == 0
            and state["duplicated_rows"] == 0
            and state["wrong_values"] == 0
            and state["record_count_matches"]
            and len(state["leaked_files"]) == 0
            and reads["read_errors"] == 0
            and gets["read_errors"] == 0
            and sqlc["read_errors"] == 0
            and subs["sub_mismatches"] == 0
            and sql["sql_battery_mismatches"] == 0
            and tag.get("tag_mismatches", 0) == 0
            and untyped == 0
        )
        return {
            "cell": sc.name,
            "schema": sc.schema,
            "bucket": sc.bucket,
            "cdc_format": sc.cdc_format,
            "cluster": sc.cluster,
            "wall_s": round(wall_s, 2),
            "consistent": consistent,
            "accepted_commits": len(landed),
            "expected_unique_keys": len(expected),
            "final_rows": state["final_rows"],
            "total_record_count": state["total_record_count"],
            "record_count_matches": state["record_count_matches"],
            "lost_rows": state["lost_rows"],
            "duplicated_rows": state["duplicated_rows"],
            "wrong_values": state["wrong_values"],
            "gw_sheds_untyped": untyped,
            "gw_sub_rows": cell.get("gw_sub_rows", 0),
            "churn_io_faults": cell.get("churn_io_faults", 0),
            **stats,
            **subs,
            **sql,
            **tag,
            **consumers,
            "pinned_reads_ok": reads["reads_ok"],
            "pinned_read_errors": reads["read_errors"],
            "getter_reads_ok": gets["reads_ok"],
            "getter_read_errors": gets["read_errors"],
            "sql_client_ok": sqlc["reads_ok"],
            "sql_client_errors": sqlc["read_errors"],
            "orphans_removed": state["orphans_removed"],
            "leaked_file_count": len(state["leaked_files"]),
            "leaked_files": state["leaked_files"][:10],
            "inconsistencies": cell["inconsistencies"][:10],
            "errors": cell["errors"][:5],
        }

    # ---- the matrix ----------------------------------------------------
    def run(self) -> dict:
        from ..metrics import gateway_metrics, registry

        os.makedirs(self.run_root, exist_ok=True)
        os.makedirs(self.warehouse_posix, exist_ok=True)
        t0 = time.monotonic()
        for sc in self.cfg.scenarios:
            # the untyped-shed gate is a per-cell DELTA of the process-global
            # counter — stash the baseline on the cell before it runs
            baseline = gateway_metrics().counter("sheds_untyped").count
            try:
                report = self.run_cell(sc)
            except Exception:
                self._heal_chaos()
                report = {
                    "cell": sc.name,
                    "consistent": False,
                    "errors": [f"cell crashed:\n{traceback.format_exc()}"],
                }
                self.cells.append(report)
            report.setdefault("gw_sheds_untyped", None)
            if report.get("gw_sheds_untyped") is None:
                report["gw_sheds_untyped"] = (
                    gateway_metrics().counter("sheds_untyped").count - baseline
                )
        from ..metrics import Counter, Gauge, Histogram

        groups: dict[str, int] = {}
        for (name, _tags), group in registry.groups.items():
            total = 0
            for m in group.metrics.values():
                if isinstance(m, (Counter, Histogram)):
                    total += m.count
                elif isinstance(m, Gauge) and m.value:
                    total += 1
            groups[name] = groups.get(name, 0) + total
        metric_census = {g: groups.get(g, 0) for g in METRIC_GROUPS}
        kinds_killed = sorted(k for k, v in self.kills_by_kind.items() if v > 0)
        points_fired = sorted(
            p for p, v in self.kills_by_point.items() if v > 0 and p != "random-sigkill"
        )
        return {
            "consistent": all(c.get("consistent") for c in self.cells),
            "wall_s": round(time.monotonic() - t0, 2),
            "cells": self.cells,
            "kills_total": self.counts["procs_killed"],
            "kills_by_kind": self.kills_by_kind,
            "kills_by_point": self.kills_by_point,
            "process_kinds_killed": kinds_killed,
            "crash_points_fired": points_fired,
            "metric_groups": metric_census,
            **self.counts,
        }


def run_mega_soak(base_dir: str, cfg: "MegaConfig | None" = None) -> dict:
    """Stand up the full stack per scenario cell under `base_dir` (one
    chaos warehouse), run the matrix, return the cross-plane report."""
    return MegaSoakSupervisor(base_dir, cfg).run()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _gateway_writer_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="mega_soak gateway-writer")
    ap.add_argument("--table", required=True)
    ap.add_argument("--gateway", required=True, help="host:port")
    ap.add_argument("--wid", type=int, required=True)
    ap.add_argument("--schema", default="kv", choices=("kv", "dict", "wide"))
    ap.add_argument("--journal", required=True)
    ap.add_argument("--stop-file", required=True, dest="stop_file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--incarnation", type=int, default=0)
    ap.add_argument("--rows-per-commit", type=int, default=120, dest="rows_per_commit")
    ap.add_argument("--update-fraction", type=float, default=0.25, dest="update_fraction")
    ap.add_argument("--max-rounds", type=int, default=10**9, dest="max_rounds")
    ap.add_argument("--tenant", default="ingest")
    return ap.parse_args(argv)


def _getter_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="mega_soak getter")
    ap.add_argument("--gateway", required=True)
    ap.add_argument("--gid", type=int, required=True)
    ap.add_argument("--schema", default="kv", choices=("kv", "dict", "wide"))
    ap.add_argument("--gw-writers", type=int, default=2, dest="gw_writers")
    ap.add_argument("--window", type=int, default=4000)
    ap.add_argument("--log", required=True)
    ap.add_argument("--stop-file", required=True, dest="stop_file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenant", default="serve")
    return ap.parse_args(argv)


def _sql_client_args(argv):
    import argparse

    ap = argparse.ArgumentParser(prog="mega_soak sql-client")
    ap.add_argument("--gateway", required=True)
    ap.add_argument("--cid", type=int, required=True)
    ap.add_argument("--schema", default="kv", choices=("kv", "dict", "wide"))
    ap.add_argument("--ident", required=True, help="catalog table identifier (db.table)")
    ap.add_argument("--cluster", action="store_true")
    ap.add_argument("--log", required=True)
    ap.add_argument("--stop-file", required=True, dest="stop_file")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenant", default="analytics")
    return ap.parse_args(argv)


def main(argv: "list[str] | None" = None) -> int:
    import argparse
    import tempfile

    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "gateway-writer":
        return gateway_writer_main(_gateway_writer_args(argv[1:]))
    if argv and argv[0] == "getter":
        return getter_main(_getter_args(argv[1:]))
    if argv and argv[0] == "sql-client":
        return sql_client_main(_sql_client_args(argv[1:]))

    ap = argparse.ArgumentParser(description="paimon-tpu production mega-soak")
    ap.add_argument("base_dir", nargs="?", default=None)
    ap.add_argument("--duration", type=float, default=45.0, help="seconds per scenario cell")
    ap.add_argument("--workers", type=int, default=2, help="cluster workers (cluster cells)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--cells",
        default="",
        help="comma-separated cell names to run (default: the full matrix)",
    )
    ap.add_argument("--kill-period", type=float, default=9.0, help="mean s between random SIGKILLs (0=off)")
    ap.add_argument("--chaos-read-ms", type=float, default=1.0)
    ap.add_argument("--chaos-write-ms", type=float, default=0.5)
    ap.add_argument("--chaos-possibility", type=int, default=200, help="one op in N faults (0=off)")
    ap.add_argument("--min-kills", type=int, default=0, help="fail unless >= N kills were survived")
    ap.add_argument("--min-kill-kinds", type=int, default=0, help="fail unless >= N distinct process kinds died")
    args = ap.parse_args(argv)
    base = args.base_dir or tempfile.mkdtemp(prefix="paimon_mega_soak_")
    scenarios = DEFAULT_MATRIX
    if args.cells:
        wanted = {c.strip() for c in args.cells.split(",") if c.strip()}
        unknown = wanted - {s.name for s in DEFAULT_MATRIX}
        if unknown:
            print(f"unknown cells: {sorted(unknown)}", file=sys.stderr)
            return 2
        scenarios = tuple(s for s in DEFAULT_MATRIX if s.name in wanted)
    cfg = MegaConfig(
        duration_s=args.duration,
        cluster_workers=args.workers,
        seed=args.seed,
        scenarios=scenarios,
        kill_period_s=args.kill_period,
        chaos_read_ms=args.chaos_read_ms,
        chaos_write_ms=args.chaos_write_ms,
        chaos_possibility=args.chaos_possibility,
    )
    report = run_mega_soak(base, cfg)
    print(json.dumps(report, indent=2, default=str))
    ok = report["consistent"]
    if report["kills_total"] < args.min_kills:
        ok = False
        print(
            f"FAIL: only {report['kills_total']} kills survived (expected >= {args.min_kills})",
            file=sys.stderr,
        )
    if len(report["process_kinds_killed"]) < args.min_kill_kinds:
        ok = False
        print(
            f"FAIL: only {report['process_kinds_killed']} process kinds died "
            f"(expected >= {args.min_kill_kinds})",
            file=sys.stderr,
        )
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
