"""Arrow Flight server: the network half of the engine surface.

Any Flight-speaking engine (or another process of this framework) can scan
tables without loading our code: ``list_flights`` enumerates tables,
``get_flight_info`` plans the scan and returns one endpoint per split (the
ticket embeds the serialized split, exactly how PaimonInputFormat hands
table splits to Hive as engine splits), and ``do_get`` streams that split's
merge-read as Arrow record batches.  Reference anchors:
paimon-hive-connector-common PaimonInputFormat (splits as engine splits),
flink/source/FlinkSourceBuilder (scan topology), service/ KvQueryServer
(this repo's JSON-over-TCP service — Flight is its columnar sibling).

The server mounts a catalog root (warehouse path): descriptors are
``db.table`` paths.  Tickets are self-contained JSON so endpoints can be
fetched from any worker, in any order, in parallel.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = ["PaimonFlightServer", "flight_scan"]


def _require_flight():
    import pyarrow.flight as flight

    return flight


class PaimonFlightServer:
    """``serve in a background thread``:

        srv = PaimonFlightServer(warehouse)
        location = srv.start()          # grpc://127.0.0.1:<port>
        ...
        srv.shutdown()
    """

    def __init__(self, warehouse: str, host: str = "127.0.0.1", port: int = 0):
        flight = _require_flight()
        outer = self

        class _Server(flight.FlightServerBase):
            def __init__(self):
                super().__init__(location=f"grpc://{host}:{port}")

            # -- discovery ------------------------------------------------
            def list_flights(self, context, criteria):
                cat = outer._catalog()
                for db in cat.list_databases():
                    for name in cat.list_tables(db):
                        ident = f"{db}.{name}"
                        desc = flight.FlightDescriptor.for_path(ident.encode())
                        # discovery stays metadata-cheap: no scan planning
                        # here — endpoints come from get_flight_info
                        yield outer._info(flight, desc, ident, plan=False)

            def get_flight_info(self, context, descriptor):
                ident = descriptor.path[0].decode()
                return outer._info(flight, descriptor, ident)

            def get_schema(self, context, descriptor):
                from ..interop.arrow_surface import arrow_schema

                t = outer._table(descriptor.path[0].decode())
                return flight.SchemaResult(arrow_schema(t.row_type))

            # -- data plane -----------------------------------------------
            def do_get(self, context, ticket):
                from ..interop.arrow_surface import record_batch_reader
                from ..table.read import DataSplit

                req = json.loads(ticket.ticket.decode())
                t = outer._table(req["table"])
                splits = [DataSplit.from_dict(d) for d in req["splits"]]
                reader = record_batch_reader(t, projection=req.get("projection"), splits=splits)
                return flight.RecordBatchStream(reader)

        self.warehouse = warehouse
        self._host = host
        self._server = _Server()
        self._thread = None
        self._cat = None

    # ---- catalog plumbing ----------------------------------------------
    def _catalog(self):
        if self._cat is None:
            from ..catalog import FileSystemCatalog

            self._cat = FileSystemCatalog(self.warehouse, commit_user="flight-server")
        return self._cat

    def _table(self, ident: str) -> "FileStoreTable":
        return self._catalog().get_table(ident)

    def _info(self, flight, descriptor, ident: str, plan: bool = True):
        from ..interop.arrow_surface import arrow_schema

        t = self._table(ident)
        if not plan:
            return flight.FlightInfo(arrow_schema(t.row_type), descriptor, [], -1, -1)
        splits = t.new_read_builder().new_scan().plan()
        endpoints = [
            flight.FlightEndpoint(
                json.dumps({"table": ident, "splits": [s.to_dict()]}).encode(),
                [self.location],
            )
            for s in splits
        ] or [
            # empty table: one endpoint with zero splits so readers still
            # get the schema
            flight.FlightEndpoint(json.dumps({"table": ident, "splits": []}).encode(), [self.location])
        ]
        total = sum(s.row_count for s in splits)
        return flight.FlightInfo(arrow_schema(t.row_type), descriptor, endpoints, total, -1)

    # ---- lifecycle ------------------------------------------------------
    @property
    def location(self) -> str:
        # advertise the bind host (a 0.0.0.0 bind should be fronted by the
        # host's routable name passed as `host`)
        return f"grpc://{self._host}:{self._server.port}"

    def start(self) -> str:
        import threading

        self._thread = threading.Thread(target=self._server.serve, daemon=True)
        self._thread.start()
        return self.location

    def shutdown(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def flight_scan(location: str, ident: str):
    """Client convenience: scan a remote table into one Arrow table by
    fetching every endpoint (a real engine would fan endpoints out to its
    workers)."""
    import pyarrow as pa

    flight = _require_flight()
    client = flight.connect(location)
    try:
        info = client.get_flight_info(flight.FlightDescriptor.for_path(ident.encode()))
        tables = []
        for ep in info.endpoints:
            tables.append(client.do_get(ep.ticket).read_all())
        return pa.concat_tables(tables) if tables else info.schema.empty_table()
    finally:
        client.close()
