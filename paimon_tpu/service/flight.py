"""Arrow Flight server: the network half of the engine surface.

Any Flight-speaking engine (or another process of this framework) can scan
tables without loading our code: ``list_flights`` enumerates tables,
``get_flight_info`` plans the scan and returns one endpoint per split (the
ticket embeds the serialized split, exactly how PaimonInputFormat hands
table splits to Hive as engine splits), and ``do_get`` streams that split's
merge-read as Arrow record batches.  Reference anchors:
paimon-hive-connector-common PaimonInputFormat (splits as engine splits),
flink/source/FlinkSourceBuilder (scan topology), service/ KvQueryServer
(this repo's JSON-over-TCP service — Flight is its columnar sibling).

Ingest + load shedding (the write half). ``do_put`` streams record batches
into a table through the real TableWrite/commit path, sharing one
WriteBufferController per table so every remote ingest stream competes for
the same admission budget as local writers. When the controller is
THROTTLING/REJECTING the server answers a TYPED busy signal instead of
letting the stream block into a timeout: a FlightUnavailableError whose
message carries a ``BUSY{...}`` JSON payload with the admission state and a
``retry_after_ms`` hint derived from it. ``do_action("health")`` serves the
same `health_dict` schema as the KV server's `health` method, so a frontend
can poll before streaming at all. ``flight_put`` is the client-side
shed-and-backoff wrapper: it parses the BUSY payload, sleeps the hinted
backoff, and retries — a remote frontend degrades gracefully under writer
saturation rather than piling retries onto a saturated writer.

The server mounts a catalog root (warehouse path): descriptors are
``db.table`` paths.  Tickets are self-contained JSON so endpoints can be
fetched from any worker, in any order, in parallel.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import TYPE_CHECKING

from .shed import ShedError, ShedInfo

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = [
    "PaimonFlightServer",
    "flight_scan",
    "flight_put",
    "flight_health",
    "flight_get_batch",
    "flight_subscribe_poll",
    "flight_subscribe",
    "FlightBusyError",
]

# the BUSY payload is flat JSON (no nested braces); non-greedy because gRPC
# appends client-context text after the server message
_BUSY_RE = re.compile(r"BUSY(\{.*?\})")


def _require_flight():
    import pyarrow.flight as flight

    return flight


class FlightBusyError(ShedError):
    """The server shed this request with a typed BUSY (writer admission is
    throttling/rejecting, reads saturated, or a subscriber shed). A
    serialization of service.shed.ShedInfo: carries the server's
    flow-control snapshot and its retry-after hint — the client-side twin
    of WriterBackpressureError — plus the canonical ``shed_info`` record.
    The payload's own ``kind`` wins; an untyped legacy payload defaults to
    the ingest kind ("put")."""

    default_kind = "put"

    def __init__(self, payload: "dict | ShedInfo"):
        super().__init__(payload, message=f"ingest shed by server: {payload}")


def _parse_busy(exc: BaseException) -> dict | None:
    m = _BUSY_RE.search(str(exc))
    if not m:
        return None
    try:
        return json.loads(m.group(1))
    except json.JSONDecodeError:
        return {"busy": True, "retry_after_ms": 0}


class PaimonFlightServer:
    """``serve in a background thread``:

        srv = PaimonFlightServer(warehouse)
        location = srv.start()          # grpc://127.0.0.1:<port>
        ...
        srv.shutdown()

    `ingest_controller`: optional WriteBufferController shared by every
    do_put stream (a test or an embedding service injects one to couple the
    Flight surface to its own writers' budget). Without it each table gets
    a controller from its own `write.buffer.*` options (None when unset —
    admission off, never BUSY)."""

    def __init__(
        self,
        warehouse: str,
        host: str = "127.0.0.1",
        port: int = 0,
        ingest_controller=None,
        gateway=None,
    ):
        flight = _require_flight()
        outer = self

        class _Server(flight.FlightServerBase):
            def __init__(self):
                super().__init__(location=f"grpc://{host}:{port}")

            # -- discovery ------------------------------------------------
            def list_flights(self, context, criteria):
                cat = outer._catalog()
                for db in cat.list_databases():
                    for name in cat.list_tables(db):
                        ident = f"{db}.{name}"
                        desc = flight.FlightDescriptor.for_path(ident.encode())
                        # discovery stays metadata-cheap: no scan planning
                        # here — endpoints come from get_flight_info
                        yield outer._info(flight, desc, ident, plan=False)

            def get_flight_info(self, context, descriptor):
                ident = descriptor.path[0].decode()
                return outer._info(flight, descriptor, ident)

            def get_schema(self, context, descriptor):
                from ..interop.arrow_surface import arrow_schema

                t = outer._table(descriptor.path[0].decode())
                return flight.SchemaResult(arrow_schema(t.row_type))

            # -- data plane -----------------------------------------------
            def do_get(self, context, ticket):
                from ..interop.arrow_surface import record_batch_reader
                from ..table.read import DataSplit

                req = json.loads(ticket.ticket.decode())
                if "subscribe" in req:
                    # long-poll subscription window as one Arrow stream:
                    # row columns + __row_kind + __snapshot_id
                    return flight.RecordBatchStream(outer._subscribe_arrow(flight, req["subscribe"]))
                t = outer._table(req["table"])
                splits = [DataSplit.from_dict(d) for d in req["splits"]]
                reader = record_batch_reader(t, projection=req.get("projection"), splits=splits)
                return flight.RecordBatchStream(reader)

            def do_put(self, context, descriptor, reader, writer):
                outer._do_put(flight, descriptor, reader)

            # -- control plane --------------------------------------------
            def list_actions(self, context):
                return [
                    ("health", "writer flow-control state (admission health_dict schema); body = db.table"),
                    ("get_batch", 'batched primary-key gets; body = {"table", "keys", "partition"?} JSON'),
                    (
                        "subscribe_poll",
                        'long-poll changelog subscription; body = {"table", "consumer", '
                        '"nextSnapshot"?, "format"?, "maxBatches"?, "timeoutMs"?} JSON',
                    ),
                    ("slo", "gateway per-tenant SLO surface (empty when no gateway attached)"),
                    ("ping", "liveness"),
                ]

            def do_action(self, context, action):
                if action.type == "ping":
                    return [flight.Result(b"{}")]
                if action.type == "slo":
                    s = outer._gateway.slo() if outer._gateway is not None else {}
                    return [flight.Result(json.dumps(s).encode())]
                if action.type == "health":
                    ident = action.body.to_pybytes().decode() if action.body else ""
                    return [
                        flight.Result(json.dumps(outer._health(ident)).encode())
                    ]
                if action.type == "get_batch":
                    req = json.loads(action.body.to_pybytes().decode())
                    return [flight.Result(json.dumps(outer._get_batch(flight, req)).encode())]
                if action.type == "subscribe_poll":
                    req = json.loads(action.body.to_pybytes().decode())
                    return [flight.Result(json.dumps(outer._subscribe_poll(flight, req)).encode())]
                raise KeyError(f"unknown action {action.type!r}")

        self.warehouse = warehouse
        self._host = host
        self._ingest_controller = ingest_controller
        # optional service.gateway.Gateway: serves the `slo` action and
        # runs tenant-tagged get_batch actions through per-tenant admission
        self._gateway = gateway
        self._controllers: dict[str, object] = {}
        self._ctl_lock = threading.Lock()
        # batched get serving: one LocalTableQuery per table, behind the
        # same admission idea as do_put — at most lookup.get.max-inflight
        # concurrent get_batch actions, the next one sheds a typed BUSY
        self._queries: dict[str, object] = {}
        self._query_locks: dict[str, threading.Lock] = {}
        self._get_inflight = 0
        self._get_lock = threading.Lock()
        # changelog subscriptions: one private SubscriptionHub per table
        # (single decode-once tailer shared by every remote consumer of that
        # table through this server) + one live Subscription per consumer-id
        self._hubs: dict[str, object] = {}
        self._flight_subs: dict[tuple[str, str], object] = {}
        self._sub_lock = threading.Lock()
        self._shutdown_flag = False  # set under _sub_lock; late polls shed typed
        self._server = _Server()
        self._thread = None
        self._cat = None

    # ---- catalog plumbing ----------------------------------------------
    def _catalog(self):
        if self._cat is None:
            from ..catalog import FileSystemCatalog

            self._cat = FileSystemCatalog(self.warehouse, commit_user="flight-server")
        return self._cat

    def _table(self, ident: str) -> "FileStoreTable":
        return self._catalog().get_table(ident)

    def _info(self, flight, descriptor, ident: str, plan: bool = True):
        from ..interop.arrow_surface import arrow_schema

        t = self._table(ident)
        if not plan:
            return flight.FlightInfo(arrow_schema(t.row_type), descriptor, [], -1, -1)
        splits = t.new_read_builder().new_scan().plan()
        endpoints = [
            flight.FlightEndpoint(
                json.dumps({"table": ident, "splits": [s.to_dict()]}).encode(),
                [self.location],
            )
            for s in splits
        ] or [
            # empty table: one endpoint with zero splits so readers still
            # get the schema
            flight.FlightEndpoint(json.dumps({"table": ident, "splits": []}).encode(), [self.location])
        ]
        total = sum(s.row_count for s in splits)
        return flight.FlightInfo(arrow_schema(t.row_type), descriptor, endpoints, total, -1)

    # ---- ingest / flow control -----------------------------------------
    def _controller(self, ident: str, table: "FileStoreTable"):
        if self._ingest_controller is not None:
            return self._ingest_controller
        with self._ctl_lock:
            if ident not in self._controllers:
                from ..core.admission import WriteBufferController

                self._controllers[ident] = WriteBufferController.from_options(table.store.options)
            return self._controllers[ident]

    def _health(self, ident: str) -> dict:
        if not ident:
            if self._ingest_controller is not None:
                return self._ingest_controller.health_dict()
            return {"state": "ok"}
        table = self._table(ident)
        ctrl = self._controller(ident, table)
        return ctrl.health_dict() if ctrl is not None else {"state": "ok"}

    # ---- batched gets ---------------------------------------------------
    def _query(self, ident: str):
        with self._ctl_lock:
            q = self._queries.get(ident)
            if q is None:
                from ..table.query import LocalTableQuery

                q = self._queries[ident] = LocalTableQuery(self._table(ident))
                self._query_locks[ident] = threading.Lock()
            return q, self._query_locks[ident]

    def _get_batch(self, flight, req: dict) -> dict:
        from ..metrics import get_metrics
        from ..options import CoreOptions

        ident = req["table"]
        q, lock = self._query(ident)
        gw_tenant = None
        if self._gateway is not None:
            gw_tenant, shed = self._gateway.admit(req.get("tenant"), "get_batch")
            if shed is not None:
                self._shed(flight, shed.to_payload())
        cap = int(q.table.options.options.get(CoreOptions.LOOKUP_GET_MAX_INFLIGHT))
        with self._get_lock:
            if self._get_inflight >= cap:
                if gw_tenant is not None:
                    self._gateway.release(gw_tenant)
                get_metrics().counter("busy_rejected").inc()
                # the same typed-BUSY wire shape as the ingest side: the
                # client backs off retry_after_ms instead of timing out
                self._shed(
                    flight,
                    ShedInfo(
                        kind="get_batch",
                        state="busy-reads",
                        tenant=gw_tenant,
                        retry_after_ms=25,
                    ).to_payload(),
                )
            self._get_inflight += 1
        t0 = time.perf_counter()
        try:
            keys = [tuple(k) if isinstance(k, list) else (k,) for k in req["keys"]]
            with lock:
                q.refresh()
                res = q.get_batch(keys, tuple(req.get("partition", ())))
            return {"rows": [None if r is None else list(r) for r in res.to_pylist()]}
        finally:
            with self._get_lock:
                self._get_inflight -= 1
            if gw_tenant is not None:
                self._gateway.release(gw_tenant)
                self._gateway.observe(gw_tenant, "get_batch", t0)

    # ---- changelog subscriptions ----------------------------------------
    def _subscription(self, ident: str, consumer: str, next_snapshot: int | None):
        """The live server-side Subscription for (table, consumer): reused
        across long-polls so the hub queue keeps filling between requests.
        A client presenting a different nextSnapshot than the subscription's
        checkpoint re-anchors it (close + resubscribe; the durable consumer
        position still wins when it is older — at-least-once replay)."""
        from .subscription import SubscriberShedError, SubscriptionHub

        key = (ident, consumer)
        with self._sub_lock:
            if self._shutdown_flag:
                # racing shutdown(): re-creating the hub here would leak its
                # non-daemon tailer/heartbeat threads past server teardown —
                # answer a typed shed instead
                raise SubscriberShedError(
                    ShedInfo(
                        kind="subscribe",
                        state="shutting-down",
                        retry_after_ms=100,
                        extras={"consumer_id": consumer},
                    )
                )
            hub = self._hubs.get(ident)
            if hub is None:
                hub = self._hubs[ident] = SubscriptionHub(self._table(ident))
            sub = self._flight_subs.get(key)
            # a subscription shed between polls is NOT silently resumed: the
            # next poll hits its SubscriberShedError and answers the typed
            # BUSY (with the restart offset) once; the poll after that finds
            # the registry empty and resumes from the durable position
            if sub is not None and next_snapshot is not None and sub.checkpoint != next_snapshot and not sub.is_shed:
                sub.close()
                self._flight_subs.pop(key, None)
                sub = None
            if sub is None:
                sub = hub.subscribe(consumer_id=consumer, from_snapshot=next_snapshot)
                self._flight_subs[key] = sub
            return sub

    def _poll_window(self, flight, req: dict) -> tuple[list, int]:
        """Drain one long-poll window: up to maxBatches, blocking up to
        timeoutMs for the first. A shed subscription answers the typed BUSY
        carrying the durable restart offset (the next poll resubscribes and
        resumes from it)."""
        from .subscription import SubscriberShedError

        ident = req["table"]
        consumer = req["consumer"]
        nxt = req.get("nextSnapshot")
        timeout_s = int(req.get("timeoutMs", 1_000)) / 1000.0
        max_batches = int(req.get("maxBatches", 64))
        try:
            # inside the try: hub.subscribe itself sheds (max-subscribers,
            # a hub racing close) and must answer the SAME typed BUSY as a
            # mid-poll shed, never an untyped server error
            sub = self._subscription(ident, consumer, nxt)
        except SubscriberShedError as exc:
            payload = dict(exc.payload)
            payload.setdefault("retry_after_ms", 25)
            self._shed(flight, payload)
        batches = []
        deadline = time.monotonic() + timeout_s
        try:
            while len(batches) < max_batches:
                remaining = deadline - time.monotonic()
                b = sub.poll(timeout=max(remaining, 0.0) if not batches else 0.0)
                if b is None:
                    break
                batches.append(b)
        except SubscriberShedError as exc:
            with self._sub_lock:
                if self._flight_subs.get((ident, consumer)) is sub:
                    del self._flight_subs[(ident, consumer)]
            payload = dict(exc.payload)
            payload.setdefault("retry_after_ms", 25)
            self._shed(flight, payload)
        return batches, sub.checkpoint

    def _subscribe_poll(self, flight, req: dict) -> dict:
        """JSON long-poll: rows (kind short strings + row values) or any
        table/cdc_format.py wire format."""
        fmt = req.get("format", "rows")
        batches, checkpoint = self._poll_window(flight, req)
        out = []
        for b in batches:
            if fmt == "rows":
                out.append(
                    {
                        "snapshot": b.snapshot_id,
                        "rows": [list(r) for r in b.data.to_pylist()],
                        "kinds": b.kinds.tolist(),
                    }
                )
            else:
                from ..table.cdc_format import encode_changelog

                out.append(
                    {"snapshot": b.snapshot_id, "messages": encode_changelog(b.data, b.kinds, fmt)}
                )
        return {"batches": out, "nextSnapshot": checkpoint}

    def _subscribe_arrow(self, flight, req: dict):
        """One long-poll window as a pyarrow Table: the table's row columns
        plus __row_kind (uint8) and __snapshot_id (int64)."""
        import pyarrow as pa

        from ..interop.arrow_surface import arrow_schema

        t = self._table(req["table"])
        batches, checkpoint = self._poll_window(flight, req)
        base = arrow_schema(t.row_type)
        schema = base.append(pa.field("__row_kind", pa.uint8())).append(
            pa.field("__snapshot_id", pa.int64())
        )
        # the checkpoint rides the schema metadata so a client that received
        # only empty/partial windows still learns where to resume
        schema = schema.with_metadata({b"next_snapshot": str(checkpoint).encode()})
        if not batches:
            return pa.Table.from_arrays(
                [pa.array([], type=f.type) for f in schema], schema=schema
            )
        parts = []
        for b in batches:
            arrow = b.data.to_arrow()
            arrow = arrow.append_column("__row_kind", pa.array(b.kinds, type=pa.uint8()))
            arrow = arrow.append_column(
                "__snapshot_id", pa.array([b.snapshot_id] * b.num_rows, type=pa.int64())
            )
            parts.append(arrow.cast(pa.schema(list(schema))))
        out = pa.concat_tables(parts)
        return out.replace_schema_metadata({b"next_snapshot": str(checkpoint).encode()})

    def _shed(self, flight, health: dict):
        """Answer BUSY: a typed, parseable unavailability — never a timeout."""
        from ..metrics import soak_metrics

        soak_metrics().counter("shed_requests").inc()
        payload = dict(health)  # typed extras (e.g. a shed subscription's
        payload["busy"] = True  # consumer_id + restart next_snapshot) ride
        payload.setdefault("retry_after_ms", 0)  # along with the core shape
        payload.setdefault("state", None)
        raise flight.FlightUnavailableError("BUSY" + json.dumps(payload))

    def _do_put(self, flight, descriptor, reader) -> None:
        from ..core.admission import WriterBackpressureError
        from ..data.batch import ColumnBatch
        from ..table.write import TableWrite

        ident = descriptor.path[0].decode()
        table = self._table(ident)
        ctrl = self._controller(ident, table)
        if ctrl is not None:
            health = ctrl.health_dict()
            if health["state"] != "ok":
                # shed BEFORE reading the stream: the client learns now, not
                # after shipping every byte into a saturated writer
                self._shed(flight, health)
        try:
            data = reader.read_all()
            tw = TableWrite(table, buffer_controller=ctrl)
            try:
                batch = ColumnBatch.from_arrow(data, table.row_type)
                tw.write(batch)
                msgs = tw.prepare_commit()
            finally:
                try:
                    tw.close()
                except WriterBackpressureError:
                    # teardown flush hitting admission must not REPLACE the
                    # in-flight typed signal (or a success) during unwind —
                    # a close-time reject would otherwise unwind untyped
                    # through the finally and reach the client as a generic
                    # stream error
                    pass
            table.new_batch_write_builder().new_commit().commit(msgs)
        except WriterBackpressureError:
            # admission rejected mid-stream: nothing was buffered for the
            # rejected batch — same typed signal, client may replay
            self._shed(flight, ctrl.health_dict() if ctrl is not None else {"state": "rejecting"})

    # ---- lifecycle ------------------------------------------------------
    @property
    def location(self) -> str:
        # advertise the bind host (a 0.0.0.0 bind should be fronted by the
        # host's routable name passed as `host`)
        return f"grpc://{self._host}:{self._server.port}"

    def start(self) -> str:
        import threading

        self._thread = threading.Thread(target=self._server.serve, daemon=True)
        self._thread.start()
        return self.location

    def shutdown(self) -> None:
        with self._sub_lock:
            self._shutdown_flag = True  # late polls shed typed, never re-create a hub
            subs = list(self._flight_subs.values())
            hubs = list(self._hubs.values())
            self._flight_subs.clear()
            self._hubs.clear()
        for sub in subs:
            try:
                sub.close()
            except Exception:
                pass
        for hub in hubs:
            hub.close()
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def flight_scan(location: str, ident: str):
    """Client convenience: scan a remote table into one Arrow table by
    fetching every endpoint (a real engine would fan endpoints out to its
    workers)."""
    import pyarrow as pa

    flight = _require_flight()
    client = flight.connect(location)
    try:
        info = client.get_flight_info(flight.FlightDescriptor.for_path(ident.encode()))
        tables = []
        for ep in info.endpoints:
            tables.append(client.do_get(ep.ticket).read_all())
        return pa.concat_tables(tables) if tables else info.schema.empty_table()
    finally:
        client.close()


def flight_health(location: str, ident: str = "") -> dict:
    """Poll the server's writer flow-control state (health_dict schema)."""
    flight = _require_flight()
    client = flight.connect(location)
    try:
        results = list(client.do_action(flight.Action("health", ident.encode())))
        return json.loads(results[0].body.to_pybytes())
    finally:
        client.close()


def flight_get_batch(
    location: str,
    ident: str,
    keys,
    partition: tuple = (),
    max_retries: int = 8,
    max_backoff_ms: int = 2_000,
) -> list:
    """Shed-aware batched gets: do_action("get_batch") honoring the server's
    typed BUSY responses — parse the payload, back off retry_after_ms
    (capped), retry; FlightBusyError after max_retries sheds. Returns
    list[tuple | None] aligned with `keys` (the same contract as
    LocalTableQuery.get_batch().to_pylist())."""
    flight = _require_flight()
    client = flight.connect(location)
    body = json.dumps(
        {
            "table": ident,
            "partition": list(partition),
            "keys": [list(k) if isinstance(k, (tuple, list)) else [k] for k in keys],
        }
    ).encode()
    sheds = 0
    try:
        for attempt in range(1, max_retries + 2):
            try:
                results = list(client.do_action(flight.Action("get_batch", body)))
                rows = json.loads(results[0].body.to_pybytes())["rows"]
                return [None if r is None else tuple(r) for r in rows]
            except Exception as exc:  # noqa: BLE001 — only BUSY is retried
                payload = _parse_busy(exc)
                if payload is None:
                    raise
                sheds += 1
                if attempt > max_retries:
                    raise FlightBusyError(payload) from exc
                time.sleep(min(int(payload.get("retry_after_ms") or 25), max_backoff_ms) / 1000.0)
        raise AssertionError("unreachable")
    finally:
        client.close()


def flight_subscribe_poll(
    location: str,
    ident: str,
    consumer: str,
    next_snapshot: int | None = None,
    fmt: str = "rows",
    max_batches: int = 64,
    timeout_ms: int = 1_000,
) -> tuple[list[dict], int]:
    """One long-poll window of the changelog subscription: returns
    (batches, nextSnapshot). Each batch dict carries "snapshot" plus either
    "rows"+"kinds" (fmt="rows") or cdc wire "messages" (fmt one of the
    table/cdc_format.py formats). Pass the returned nextSnapshot into the
    next call; a typed BUSY (this consumer was shed as too slow) raises
    FlightBusyError whose payload carries the durable restart offset —
    polling again resumes from it."""
    flight = _require_flight()
    client = flight.connect(location)
    body = {
        "table": ident,
        "consumer": consumer,
        "format": fmt,
        "maxBatches": max_batches,
        "timeoutMs": timeout_ms,
    }
    if next_snapshot is not None:
        body["nextSnapshot"] = next_snapshot
    try:
        results = list(client.do_action(flight.Action("subscribe_poll", json.dumps(body).encode())))
        out = json.loads(results[0].body.to_pybytes())
        return out["batches"], out["nextSnapshot"]
    except Exception as exc:  # noqa: BLE001 — only BUSY is typed
        payload = _parse_busy(exc)
        if payload is None:
            raise
        raise FlightBusyError(payload) from exc
    finally:
        client.close()


def flight_subscribe(
    location: str,
    ident: str,
    consumer: str,
    next_snapshot: int | None = None,
    max_batches: int = 64,
    timeout_ms: int = 1_000,
):
    """Arrow long-poll subscription window via do_get: returns
    (pyarrow.Table, nextSnapshot). The table carries the row columns plus
    __row_kind (uint8) and __snapshot_id (int64); nextSnapshot comes from
    the stream's schema metadata so empty windows still advance the
    client's resume token."""
    flight = _require_flight()
    client = flight.connect(location)
    body = {
        "subscribe": {
            "table": ident,
            "consumer": consumer,
            "maxBatches": max_batches,
            "timeoutMs": timeout_ms,
        }
    }
    if next_snapshot is not None:
        body["subscribe"]["nextSnapshot"] = next_snapshot
    try:
        table = client.do_get(flight.Ticket(json.dumps(body).encode())).read_all()
        meta = table.schema.metadata or {}
        nxt = int(meta.get(b"next_snapshot", b"0"))
        return table, nxt
    except Exception as exc:  # noqa: BLE001 — only BUSY is typed
        payload = _parse_busy(exc)
        if payload is None:
            raise
        raise FlightBusyError(payload) from exc
    finally:
        client.close()


def flight_put(
    location: str,
    ident: str,
    data,
    max_retries: int = 8,
    max_backoff_ms: int = 2_000,
) -> dict:
    """Shed-aware ingest: stream `data` (a pyarrow Table) into the remote
    table, honoring the server's typed BUSY responses — parse the payload,
    back off `retry_after_ms` (capped), retry. Raises FlightBusyError after
    `max_retries` sheds, so the caller's failure mode under sustained writer
    saturation is an explicit typed signal, never a timeout. Returns
    {"attempts", "sheds", "rows", "backoff_ms"}."""
    flight = _require_flight()
    client = flight.connect(location)
    sheds = 0
    total_backoff = 0.0
    try:
        for attempt in range(1, max_retries + 2):
            try:
                writer, meta = client.do_put(
                    flight.FlightDescriptor.for_path(ident.encode()), data.schema
                )
                try:
                    writer.write_table(data)
                finally:
                    writer.close()
                return {
                    "attempts": attempt,
                    "sheds": sheds,
                    "rows": data.num_rows,
                    "backoff_ms": round(total_backoff, 1),
                }
            except Exception as exc:  # noqa: BLE001 — only BUSY is retried
                payload = _parse_busy(exc)
                if payload is None:
                    raise
                sheds += 1
                if attempt > max_retries:
                    raise FlightBusyError(payload) from exc
                backoff = min(int(payload.get("retry_after_ms") or 50), max_backoff_ms)
                total_backoff += backoff
                time.sleep(backoff / 1000.0)
        raise AssertionError("unreachable")
    finally:
        client.close()
