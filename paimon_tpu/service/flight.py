"""Arrow Flight server: the network half of the engine surface.

Any Flight-speaking engine (or another process of this framework) can scan
tables without loading our code: ``list_flights`` enumerates tables,
``get_flight_info`` plans the scan and returns one endpoint per split (the
ticket embeds the serialized split, exactly how PaimonInputFormat hands
table splits to Hive as engine splits), and ``do_get`` streams that split's
merge-read as Arrow record batches.  Reference anchors:
paimon-hive-connector-common PaimonInputFormat (splits as engine splits),
flink/source/FlinkSourceBuilder (scan topology), service/ KvQueryServer
(this repo's JSON-over-TCP service — Flight is its columnar sibling).

Ingest + load shedding (the write half). ``do_put`` streams record batches
into a table through the real TableWrite/commit path, sharing one
WriteBufferController per table so every remote ingest stream competes for
the same admission budget as local writers. When the controller is
THROTTLING/REJECTING the server answers a TYPED busy signal instead of
letting the stream block into a timeout: a FlightUnavailableError whose
message carries a ``BUSY{...}`` JSON payload with the admission state and a
``retry_after_ms`` hint derived from it. ``do_action("health")`` serves the
same `health_dict` schema as the KV server's `health` method, so a frontend
can poll before streaming at all. ``flight_put`` is the client-side
shed-and-backoff wrapper: it parses the BUSY payload, sleeps the hinted
backoff, and retries — a remote frontend degrades gracefully under writer
saturation rather than piling retries onto a saturated writer.

The server mounts a catalog root (warehouse path): descriptors are
``db.table`` paths.  Tickets are self-contained JSON so endpoints can be
fetched from any worker, in any order, in parallel.
"""

from __future__ import annotations

import json
import re
import threading
import time
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..table import FileStoreTable

__all__ = [
    "PaimonFlightServer",
    "flight_scan",
    "flight_put",
    "flight_health",
    "flight_get_batch",
    "FlightBusyError",
]

# the BUSY payload is flat JSON (no nested braces); non-greedy because gRPC
# appends client-context text after the server message
_BUSY_RE = re.compile(r"BUSY(\{.*?\})")


def _require_flight():
    import pyarrow.flight as flight

    return flight


class FlightBusyError(RuntimeError):
    """The server shed this request with a typed BUSY (writer admission is
    throttling/rejecting). Carries the server's flow-control snapshot and
    its retry-after hint — the client-side twin of WriterBackpressureError."""

    def __init__(self, payload: dict):
        super().__init__(f"ingest shed by server: {payload}")
        self.payload = payload
        self.retry_after_ms = int(payload.get("retry_after_ms", 0))


def _parse_busy(exc: BaseException) -> dict | None:
    m = _BUSY_RE.search(str(exc))
    if not m:
        return None
    try:
        return json.loads(m.group(1))
    except json.JSONDecodeError:
        return {"busy": True, "retry_after_ms": 0}


class PaimonFlightServer:
    """``serve in a background thread``:

        srv = PaimonFlightServer(warehouse)
        location = srv.start()          # grpc://127.0.0.1:<port>
        ...
        srv.shutdown()

    `ingest_controller`: optional WriteBufferController shared by every
    do_put stream (a test or an embedding service injects one to couple the
    Flight surface to its own writers' budget). Without it each table gets
    a controller from its own `write.buffer.*` options (None when unset —
    admission off, never BUSY)."""

    def __init__(
        self,
        warehouse: str,
        host: str = "127.0.0.1",
        port: int = 0,
        ingest_controller=None,
    ):
        flight = _require_flight()
        outer = self

        class _Server(flight.FlightServerBase):
            def __init__(self):
                super().__init__(location=f"grpc://{host}:{port}")

            # -- discovery ------------------------------------------------
            def list_flights(self, context, criteria):
                cat = outer._catalog()
                for db in cat.list_databases():
                    for name in cat.list_tables(db):
                        ident = f"{db}.{name}"
                        desc = flight.FlightDescriptor.for_path(ident.encode())
                        # discovery stays metadata-cheap: no scan planning
                        # here — endpoints come from get_flight_info
                        yield outer._info(flight, desc, ident, plan=False)

            def get_flight_info(self, context, descriptor):
                ident = descriptor.path[0].decode()
                return outer._info(flight, descriptor, ident)

            def get_schema(self, context, descriptor):
                from ..interop.arrow_surface import arrow_schema

                t = outer._table(descriptor.path[0].decode())
                return flight.SchemaResult(arrow_schema(t.row_type))

            # -- data plane -----------------------------------------------
            def do_get(self, context, ticket):
                from ..interop.arrow_surface import record_batch_reader
                from ..table.read import DataSplit

                req = json.loads(ticket.ticket.decode())
                t = outer._table(req["table"])
                splits = [DataSplit.from_dict(d) for d in req["splits"]]
                reader = record_batch_reader(t, projection=req.get("projection"), splits=splits)
                return flight.RecordBatchStream(reader)

            def do_put(self, context, descriptor, reader, writer):
                outer._do_put(flight, descriptor, reader)

            # -- control plane --------------------------------------------
            def list_actions(self, context):
                return [
                    ("health", "writer flow-control state (admission health_dict schema); body = db.table"),
                    ("get_batch", 'batched primary-key gets; body = {"table", "keys", "partition"?} JSON'),
                    ("ping", "liveness"),
                ]

            def do_action(self, context, action):
                if action.type == "ping":
                    return [flight.Result(b"{}")]
                if action.type == "health":
                    ident = action.body.to_pybytes().decode() if action.body else ""
                    return [
                        flight.Result(json.dumps(outer._health(ident)).encode())
                    ]
                if action.type == "get_batch":
                    req = json.loads(action.body.to_pybytes().decode())
                    return [flight.Result(json.dumps(outer._get_batch(flight, req)).encode())]
                raise KeyError(f"unknown action {action.type!r}")

        self.warehouse = warehouse
        self._host = host
        self._ingest_controller = ingest_controller
        self._controllers: dict[str, object] = {}
        self._ctl_lock = threading.Lock()
        # batched get serving: one LocalTableQuery per table, behind the
        # same admission idea as do_put — at most lookup.get.max-inflight
        # concurrent get_batch actions, the next one sheds a typed BUSY
        self._queries: dict[str, object] = {}
        self._query_locks: dict[str, threading.Lock] = {}
        self._get_inflight = 0
        self._get_lock = threading.Lock()
        self._server = _Server()
        self._thread = None
        self._cat = None

    # ---- catalog plumbing ----------------------------------------------
    def _catalog(self):
        if self._cat is None:
            from ..catalog import FileSystemCatalog

            self._cat = FileSystemCatalog(self.warehouse, commit_user="flight-server")
        return self._cat

    def _table(self, ident: str) -> "FileStoreTable":
        return self._catalog().get_table(ident)

    def _info(self, flight, descriptor, ident: str, plan: bool = True):
        from ..interop.arrow_surface import arrow_schema

        t = self._table(ident)
        if not plan:
            return flight.FlightInfo(arrow_schema(t.row_type), descriptor, [], -1, -1)
        splits = t.new_read_builder().new_scan().plan()
        endpoints = [
            flight.FlightEndpoint(
                json.dumps({"table": ident, "splits": [s.to_dict()]}).encode(),
                [self.location],
            )
            for s in splits
        ] or [
            # empty table: one endpoint with zero splits so readers still
            # get the schema
            flight.FlightEndpoint(json.dumps({"table": ident, "splits": []}).encode(), [self.location])
        ]
        total = sum(s.row_count for s in splits)
        return flight.FlightInfo(arrow_schema(t.row_type), descriptor, endpoints, total, -1)

    # ---- ingest / flow control -----------------------------------------
    def _controller(self, ident: str, table: "FileStoreTable"):
        if self._ingest_controller is not None:
            return self._ingest_controller
        with self._ctl_lock:
            if ident not in self._controllers:
                from ..core.admission import WriteBufferController

                self._controllers[ident] = WriteBufferController.from_options(table.store.options)
            return self._controllers[ident]

    def _health(self, ident: str) -> dict:
        if not ident:
            if self._ingest_controller is not None:
                return self._ingest_controller.health_dict()
            return {"state": "ok"}
        table = self._table(ident)
        ctrl = self._controller(ident, table)
        return ctrl.health_dict() if ctrl is not None else {"state": "ok"}

    # ---- batched gets ---------------------------------------------------
    def _query(self, ident: str):
        with self._ctl_lock:
            q = self._queries.get(ident)
            if q is None:
                from ..table.query import LocalTableQuery

                q = self._queries[ident] = LocalTableQuery(self._table(ident))
                self._query_locks[ident] = threading.Lock()
            return q, self._query_locks[ident]

    def _get_batch(self, flight, req: dict) -> dict:
        from ..metrics import get_metrics
        from ..options import CoreOptions

        ident = req["table"]
        q, lock = self._query(ident)
        cap = int(q.table.options.options.get(CoreOptions.LOOKUP_GET_MAX_INFLIGHT))
        with self._get_lock:
            if self._get_inflight >= cap:
                get_metrics().counter("busy_rejected").inc()
                # the same typed-BUSY wire shape as the ingest side: the
                # client backs off retry_after_ms instead of timing out
                self._shed(flight, {"state": "busy-reads", "retry_after_ms": 25})
            self._get_inflight += 1
        try:
            keys = [tuple(k) if isinstance(k, list) else (k,) for k in req["keys"]]
            with lock:
                q.refresh()
                res = q.get_batch(keys, tuple(req.get("partition", ())))
            return {"rows": [None if r is None else list(r) for r in res.to_pylist()]}
        finally:
            with self._get_lock:
                self._get_inflight -= 1

    def _shed(self, flight, health: dict):
        """Answer BUSY: a typed, parseable unavailability — never a timeout."""
        from ..metrics import soak_metrics

        soak_metrics().counter("shed_requests").inc()
        payload = {
            "busy": True,
            "state": health.get("state"),
            "buffered_bytes": health.get("buffered_bytes"),
            "pending_flushes": health.get("pending_flushes"),
            "retry_after_ms": health.get("retry_after_ms", 0),
        }
        raise flight.FlightUnavailableError("BUSY" + json.dumps(payload))

    def _do_put(self, flight, descriptor, reader) -> None:
        from ..core.admission import WriterBackpressureError
        from ..data.batch import ColumnBatch
        from ..table.write import TableWrite

        ident = descriptor.path[0].decode()
        table = self._table(ident)
        ctrl = self._controller(ident, table)
        if ctrl is not None:
            health = ctrl.health_dict()
            if health["state"] != "ok":
                # shed BEFORE reading the stream: the client learns now, not
                # after shipping every byte into a saturated writer
                self._shed(flight, health)
        try:
            data = reader.read_all()
            tw = TableWrite(table, buffer_controller=ctrl)
            try:
                batch = ColumnBatch.from_arrow(data, table.row_type)
                tw.write(batch)
                msgs = tw.prepare_commit()
            finally:
                tw.close()
            table.new_batch_write_builder().new_commit().commit(msgs)
        except WriterBackpressureError:
            # admission rejected mid-stream: nothing was buffered for the
            # rejected batch — same typed signal, client may replay
            self._shed(flight, ctrl.health_dict() if ctrl is not None else {"state": "rejecting"})

    # ---- lifecycle ------------------------------------------------------
    @property
    def location(self) -> str:
        # advertise the bind host (a 0.0.0.0 bind should be fronted by the
        # host's routable name passed as `host`)
        return f"grpc://{self._host}:{self._server.port}"

    def start(self) -> str:
        import threading

        self._thread = threading.Thread(target=self._server.serve, daemon=True)
        self._thread.start()
        return self.location

    def shutdown(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


def flight_scan(location: str, ident: str):
    """Client convenience: scan a remote table into one Arrow table by
    fetching every endpoint (a real engine would fan endpoints out to its
    workers)."""
    import pyarrow as pa

    flight = _require_flight()
    client = flight.connect(location)
    try:
        info = client.get_flight_info(flight.FlightDescriptor.for_path(ident.encode()))
        tables = []
        for ep in info.endpoints:
            tables.append(client.do_get(ep.ticket).read_all())
        return pa.concat_tables(tables) if tables else info.schema.empty_table()
    finally:
        client.close()


def flight_health(location: str, ident: str = "") -> dict:
    """Poll the server's writer flow-control state (health_dict schema)."""
    flight = _require_flight()
    client = flight.connect(location)
    try:
        results = list(client.do_action(flight.Action("health", ident.encode())))
        return json.loads(results[0].body.to_pybytes())
    finally:
        client.close()


def flight_get_batch(
    location: str,
    ident: str,
    keys,
    partition: tuple = (),
    max_retries: int = 8,
    max_backoff_ms: int = 2_000,
) -> list:
    """Shed-aware batched gets: do_action("get_batch") honoring the server's
    typed BUSY responses — parse the payload, back off retry_after_ms
    (capped), retry; FlightBusyError after max_retries sheds. Returns
    list[tuple | None] aligned with `keys` (the same contract as
    LocalTableQuery.get_batch().to_pylist())."""
    flight = _require_flight()
    client = flight.connect(location)
    body = json.dumps(
        {
            "table": ident,
            "partition": list(partition),
            "keys": [list(k) if isinstance(k, (tuple, list)) else [k] for k in keys],
        }
    ).encode()
    sheds = 0
    try:
        for attempt in range(1, max_retries + 2):
            try:
                results = list(client.do_action(flight.Action("get_batch", body)))
                rows = json.loads(results[0].body.to_pybytes())["rows"]
                return [None if r is None else tuple(r) for r in rows]
            except Exception as exc:  # noqa: BLE001 — only BUSY is retried
                payload = _parse_busy(exc)
                if payload is None:
                    raise
                sheds += 1
                if attempt > max_retries:
                    raise FlightBusyError(payload) from exc
                time.sleep(min(int(payload.get("retry_after_ms") or 25), max_backoff_ms) / 1000.0)
        raise AssertionError("unreachable")
    finally:
        client.close()


def flight_put(
    location: str,
    ident: str,
    data,
    max_retries: int = 8,
    max_backoff_ms: int = 2_000,
) -> dict:
    """Shed-aware ingest: stream `data` (a pyarrow Table) into the remote
    table, honoring the server's typed BUSY responses — parse the payload,
    back off `retry_after_ms` (capped), retry. Raises FlightBusyError after
    `max_retries` sheds, so the caller's failure mode under sustained writer
    saturation is an explicit typed signal, never a timeout. Returns
    {"attempts", "sheds", "rows", "backoff_ms"}."""
    flight = _require_flight()
    client = flight.connect(location)
    sheds = 0
    total_backoff = 0.0
    try:
        for attempt in range(1, max_retries + 2):
            try:
                writer, meta = client.do_put(
                    flight.FlightDescriptor.for_path(ident.encode()), data.schema
                )
                try:
                    writer.write_table(data)
                finally:
                    writer.close()
                return {
                    "attempts": attempt,
                    "sheds": sheds,
                    "rows": data.num_rows,
                    "backoff_ms": round(total_backoff, 1),
                }
            except Exception as exc:  # noqa: BLE001 — only BUSY is retried
                payload = _parse_busy(exc)
                if payload is None:
                    raise
                sheds += 1
                if attempt > max_retries:
                    raise FlightBusyError(payload) from exc
                backoff = min(int(payload.get("retry_after_ms") or 50), max_backoff_ms)
                total_backoff += backoff
                time.sleep(backoff / 1000.0)
        raise AssertionError("unreachable")
    finally:
        client.close()
