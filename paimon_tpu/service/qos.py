"""Per-tenant QoS: weighted-fair byte budgets, decayed latency SLOs.

The gateway (service/gateway.py) fronts every request kind — put,
get_batch, subscribe poll, SQL — and needs to answer two questions per
request: *may this tenant run now* (admission) and *how is each tenant
doing* (the SLO surface). This module is both answers, deliberately free
of any transport so the KV server, Flight server, and in-process gateway
share one implementation:

  TenantBudget      one tenant's token/byte bucket + in-flight cap. The
                    byte budget is the PR 8 WriteBufferController idea
                    (admit-or-typed-shed, never queue-into-timeout)
                    generalized from buffered memtable bytes to request
                    bytes per second, with the refill rate set by
                    weighted-fair division of the global budget.
  QosController     the tenant table: parses gateway.tenant.<id>.* keys,
                    lands untagged traffic in the "default" tenant,
                    recomputes weighted-fair shares as tenants appear,
                    and turns every refusal into a canonical ShedInfo.
  DecayedHistogram  log-bucketed latency histogram with exponential
                    time decay — p50/p99 that track *current* behavior
                    (metrics.Histogram's 100-sample window is too small
                    and too eviction-ordered for per-(tenant, kind) SLOs).
  SloTracker        per-(tenant, kind) histograms + admitted/shed/hedged
                    counters feeding gateway.slo().

Everything takes an injectable monotonic clock so the refill math and
decay curves are unit-testable without sleeping.
"""

from __future__ import annotations

import math
import threading
import time

import numpy as np

from .shed import ShedInfo

__all__ = [
    "DecayedHistogram",
    "TenantBudget",
    "QosController",
    "SloTracker",
    "parse_tenant_configs",
]

_TENANT_PREFIX = "gateway.tenant."
DEFAULT_TENANT = "default"


# ---------------------------------------------------------------------------
# decayed latency histogram


class DecayedHistogram:
    """Latency histogram over log-spaced millisecond buckets whose weights
    decay as exp(-age / tau): a sample recorded `tau` seconds ago counts
    ~0.37 of a fresh one. Percentiles therefore answer "what is the p99
    *right now*", not "what was the p99 since process start" — the property
    the storm asserts when a quiet tenant's p99 must stay flat while a
    greedy one is being shed.

    Bounds run 0.05 ms .. 2 min at a 1.25 geometric factor (~70 buckets);
    a sample reports as its bucket's upper bound, so percentiles are
    conservative (never under-reported) and bounded-error (<= 25%)."""

    def __init__(self, tau_s: float = 30.0, clock=time.monotonic):
        self._tau = float(tau_s)
        self._clock = clock
        bounds = [0.05]
        while bounds[-1] < 120_000.0:
            bounds.append(bounds[-1] * 1.25)
        self._bounds = np.asarray(bounds, dtype=np.float64)
        # one overflow bucket past the last bound
        self._weights = np.zeros(len(bounds) + 1, dtype=np.float64)
        self._last = clock()
        self._lock = threading.Lock()
        self._total_samples = 0  # lifetime, undecayed

    def _decay_locked(self) -> None:
        now = self._clock()
        dt = now - self._last
        if dt > 0:
            self._weights *= math.exp(-dt / self._tau)
            self._last = now

    def update(self, latency_ms: float) -> None:
        with self._lock:
            self._decay_locked()
            idx = int(np.searchsorted(self._bounds, float(latency_ms), side="left"))
            self._weights[idx] += 1.0
            self._total_samples += 1

    def percentile(self, p: float) -> float:
        """p in [0, 100]; 0.0 on an empty (or fully decayed) window."""
        with self._lock:
            self._decay_locked()
            total = float(self._weights.sum())
            if total <= 1e-9:
                return 0.0
            target = total * min(max(p, 0.0), 100.0) / 100.0
            cum = np.cumsum(self._weights)
            idx = int(np.searchsorted(cum, target, side="left"))
            if idx >= len(self._bounds):
                return float(self._bounds[-1] * 1.25)
            return float(self._bounds[idx])

    def decayed_count(self) -> float:
        with self._lock:
            self._decay_locked()
            return float(self._weights.sum())

    @property
    def total_samples(self) -> int:
        return self._total_samples


# ---------------------------------------------------------------------------
# tenant budgets


class TenantBudget:
    """One tenant's admission state: an in-flight request cap plus a token
    bucket over request bytes. Tokens refill continuously at the effective
    rate (weighted-fair share, see QosController.reshare) up to one
    second's burst; admission either succeeds atomically (inflight slot
    claimed, bytes debited) or returns a ShedInfo with the *exact* refill
    deadline as retry_after_ms — a shed client that sleeps the hint is
    admitted on its next try instead of discovering the budget by retry
    storm."""

    def __init__(
        self,
        tenant: str,
        weight: float = 1.0,
        max_inflight: int = 64,
        bytes_per_sec_cap: int = 0,
        retry_after_ms: int = 25,
        clock=time.monotonic,
    ):
        self.tenant = tenant
        self.weight = float(weight)
        self.max_inflight = int(max_inflight)
        # hard per-tenant cap (gateway.tenant.<id>.bytes-per-sec; 0 = none)
        self.bytes_per_sec_cap = int(bytes_per_sec_cap)
        self._retry_after_ms = int(retry_after_ms)
        self._clock = clock
        self._lock = threading.Lock()
        self._inflight = 0
        # effective refill rate after weighted-fair division; 0 = unlimited
        self._rate = 0.0
        self._tokens = 0.0
        self._burst = 0.0
        self._last = clock()
        self._admitted = 0
        self._shed = 0

    def set_rate(self, rate: float) -> None:
        """Install the weighted-fair effective rate (bytes/sec; 0 = no byte
        limit). The bucket starts full at one second of burst."""
        with self._lock:
            self._refill_locked()
            self._rate = float(rate)
            self._burst = max(self._rate, 1.0)
            self._tokens = min(self._tokens, self._burst) if self._tokens else self._burst

    def _refill_locked(self) -> None:
        now = self._clock()
        dt = now - self._last
        self._last = now
        if self._rate > 0 and dt > 0:
            self._tokens = min(self._burst, self._tokens + dt * self._rate)

    def try_admit(self, nbytes: int = 0, kind: str = "request") -> "ShedInfo | None":
        """None = admitted (inflight claimed, bytes debited). Otherwise the
        typed refusal; the caller has NOT consumed anything."""
        with self._lock:
            self._refill_locked()
            if self._inflight >= self.max_inflight:
                self._shed += 1
                return ShedInfo(
                    kind=kind,
                    state="busy-inflight",
                    tenant=self.tenant,
                    retry_after_ms=self._retry_after_ms,
                    extras={"inflight": self._inflight, "max_inflight": self.max_inflight},
                )
            if self._rate > 0 and nbytes > self._tokens:
                deficit = float(nbytes) - self._tokens
                retry = max(1, int(math.ceil(deficit / self._rate * 1000.0)))
                self._shed += 1
                return ShedInfo(
                    kind=kind,
                    state="throttling-bytes",
                    tenant=self.tenant,
                    retry_after_ms=retry,
                    extras={"bytes_per_sec": int(self._rate), "requested_bytes": int(nbytes)},
                )
            self._inflight += 1
            if self._rate > 0:
                self._tokens -= float(nbytes)
            self._admitted += 1
            return None

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def snapshot(self) -> dict:
        """Budget-utilization slice of the SLO surface."""
        with self._lock:
            self._refill_locked()
            util = 0.0
            if self._rate > 0 and self._burst > 0:
                util = round(1.0 - self._tokens / self._burst, 4)
            return {
                "weight": self.weight,
                "inflight": self._inflight,
                "max_inflight": self.max_inflight,
                "bytes_per_sec": int(self._rate),
                "tokens": int(self._tokens),
                "budget_utilization": util,
                "admitted": self._admitted,
                "shed": self._shed,
                "retry_after_ms": self._retry_after_ms,
            }


def parse_tenant_configs(options) -> dict[str, dict]:
    """Prefix-scan an Options map for gateway.tenant.<id>.{weight,
    max-inflight,bytes-per-sec} keys -> {tenant: {weight, max_inflight,
    bytes_per_sec}} (missing props absent, filled by QosController)."""
    from ..options import MemorySize

    out: dict[str, dict] = {}
    for key, value in options.to_map().items():
        if not key.startswith(_TENANT_PREFIX):
            continue
        rest = key[len(_TENANT_PREFIX):]
        tenant, _, prop = rest.rpartition(".")
        if not tenant:
            continue
        cfg = out.setdefault(tenant, {})
        if prop == "weight":
            cfg["weight"] = float(value)
        elif prop == "max-inflight":
            cfg["max_inflight"] = int(value)
        elif prop == "bytes-per-sec":
            cfg["bytes_per_sec"] = int(MemorySize.parse(value))
    return out


class QosController:
    """The gateway's tenant table. Admission is two layers deep: the
    tenant's in-flight cap, then its token bucket refilled at
    min(per-tenant cap, global_rate * weight / sum(weights across all
    known tenants)). Untagged traffic (tenant=None) lands in "default";
    tenants not named in the options are created on first sight with
    default weight/caps and the shares recomputed, so fairness always
    divides over the tenants that actually exist."""

    def __init__(self, options=None, clock=time.monotonic):
        from ..options import CoreOptions, Options

        options = options if options is not None else Options()
        self._clock = clock
        self._lock = threading.Lock()
        self._default_max_inflight = int(options.get(CoreOptions.GATEWAY_MAX_INFLIGHT))
        self._global_rate = int(options.get(CoreOptions.GATEWAY_BYTES_PER_SEC))
        self._retry_after_ms = int(options.get(CoreOptions.GATEWAY_RETRY_AFTER))
        self._configs = parse_tenant_configs(options)
        self._budgets: dict[str, TenantBudget] = {}
        for tenant in sorted(self._configs):
            self._ensure_locked(tenant)
        self._ensure_locked(DEFAULT_TENANT)
        self._reshare_locked()

    def _ensure_locked(self, tenant: str) -> TenantBudget:
        b = self._budgets.get(tenant)
        if b is None:
            cfg = self._configs.get(tenant, {})
            b = TenantBudget(
                tenant,
                weight=cfg.get("weight", 1.0),
                max_inflight=cfg.get("max_inflight", self._default_max_inflight),
                bytes_per_sec_cap=cfg.get("bytes_per_sec", 0),
                retry_after_ms=self._retry_after_ms,
                clock=self._clock,
            )
            self._budgets[tenant] = b
        return b

    def _reshare_locked(self) -> None:
        total_w = sum(b.weight for b in self._budgets.values()) or 1.0
        for b in self._budgets.values():
            fair = self._global_rate * b.weight / total_w if self._global_rate > 0 else 0.0
            if b.bytes_per_sec_cap > 0:
                rate = min(fair, b.bytes_per_sec_cap) if fair > 0 else float(b.bytes_per_sec_cap)
            else:
                rate = fair
            b.set_rate(rate)

    def budget(self, tenant: "str | None") -> TenantBudget:
        name = tenant or DEFAULT_TENANT
        with self._lock:
            if name not in self._budgets:
                self._ensure_locked(name)
                self._reshare_locked()
            return self._budgets[name]

    def admit(self, tenant: "str | None", kind: str, nbytes: int = 0) -> "tuple[str, ShedInfo | None]":
        """(resolved tenant name, None) on admission — the caller MUST
        release(tenant) when the request finishes. (name, ShedInfo) on a
        typed refusal (nothing consumed)."""
        b = self.budget(tenant)
        return b.tenant, b.try_admit(nbytes, kind=kind)

    def release(self, tenant: "str | None") -> None:
        self.budget(tenant).release()

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(self._budgets)

    def snapshot(self) -> dict:
        with self._lock:
            return {name: b.snapshot() for name, b in sorted(self._budgets.items())}


# ---------------------------------------------------------------------------
# SLO surface


class SloTracker:
    """Per-(tenant, kind) decayed latency histograms plus admitted / shed /
    hedged counters: the numbers behind gateway.slo() and the KV/Flight
    'slo' health-style action. Counters are lifetime (monotonic — the
    storm diffs them); percentiles are decayed (current behavior)."""

    KINDS = ("put", "get_batch", "subscribe", "sql")

    def __init__(self, tau_s: float = 30.0, clock=time.monotonic):
        self._tau = float(tau_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._hists: dict[tuple[str, str], DecayedHistogram] = {}
        self._counts: dict[tuple[str, str], dict] = {}

    def _slot(self, tenant: str, kind: str) -> tuple[DecayedHistogram, dict]:
        key = (tenant, kind)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = DecayedHistogram(self._tau, clock=self._clock)
                self._hists[key] = h
                self._counts[key] = {"admitted": 0, "shed": 0, "hedged": 0}
            return h, self._counts[key]

    def record(self, tenant: str, kind: str, latency_ms: float, *, hedged: bool = False) -> None:
        h, c = self._slot(tenant, kind)
        h.update(latency_ms)
        with self._lock:
            c["admitted"] += 1
            if hedged:
                c["hedged"] += 1

    def record_shed(self, tenant: str, kind: str) -> None:
        _, c = self._slot(tenant, kind)
        with self._lock:
            c["shed"] += 1

    def percentile(self, tenant: str, kind: str, p: float) -> float:
        h, _ = self._slot(tenant, kind)
        return h.percentile(p)

    def slo(self, qos: "QosController | None" = None) -> dict:
        """{tenant: {"kinds": {kind: {p50_ms, p99_ms, samples, admitted,
        shed, hedged}}, "budget": {...}}} — the per-tenant SLO surface."""
        with self._lock:
            keys = list(self._hists)
        tenants: dict[str, dict] = {}
        for tenant, kind in keys:
            h, c = self._slot(tenant, kind)
            entry = tenants.setdefault(tenant, {"kinds": {}})
            with self._lock:
                counts = dict(c)
            entry["kinds"][kind] = {
                "p50_ms": round(h.percentile(50), 3),
                "p99_ms": round(h.percentile(99), 3),
                "samples": h.total_samples,
                **counts,
            }
        if qos is not None:
            for tenant, budget in qos.snapshot().items():
                tenants.setdefault(tenant, {"kinds": {}})["budget"] = budget
        return tenants
