"""Canonical typed-shed protocol (ISSUE 17): one wire shape for every BUSY.

The service plane grew three independent typed-BUSY dialects — the KV
server's ``{"busy": true, "state": ..., "retry_after_ms": ...}`` JSON
response (KvBusyError), the Flight server's ``BUSY{...}`` message payload
(FlightBusyError), and the subscription hub's shed payload carrying a
durable restart offset (SubscriberShedError). They agreed on spirit
(typed, parseable, retry-after-hinted, never a queue-into-timeout) but not
on shape, so nothing above them could reason about load generically.

``ShedInfo`` is the one canonical record all three serialize:

    kind            what was shed: put | get_batch | subscribe | sql | request
    state           why: the admission health state ("throttling",
                    "rejecting", "busy-reads", "queue-full",
                    "buffer-exhausted", "busy-subscribers", "busy-inflight",
                    "throttling-bytes", "shutting-down", ...)
    tenant          who (gateway multi-tenant admission; None = untagged)
    retry_after_ms  the server's backoff hint
    restart_offset  durable resume position for stateful kinds (a shed
                    subscriber's next snapshot); None elsewhere
    extras          any legacy payload fields that ride along unharmed

``to_payload()`` emits the flat wire dict every legacy client already
parses (``busy``/``state``/``retry_after_ms`` plus the subscription's
``consumer_id``/``next_snapshot`` aliases), so the three legacy exception
types become thin serializations of ShedInfo — their constructor and
attribute contracts are unchanged, old clients keep working, and new code
reads ``exc.shed_info`` for the canonical record.
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["ShedInfo", "ShedError", "GatewayShedError"]

# payload keys owned by the canonical record (everything else is extras)
_CORE_KEYS = frozenset(
    {"busy", "kind", "state", "tenant", "retry_after_ms", "restart_offset", "next_snapshot"}
)


@dataclass
class ShedInfo:
    """One typed shed, serializable to the flat wire payload every legacy
    BUSY client already understands."""

    kind: str = "request"
    state: str | None = None
    tenant: str | None = None
    retry_after_ms: int = 0
    restart_offset: int | None = None
    extras: dict = field(default_factory=dict)

    def to_payload(self) -> dict:
        """Flat wire dict: the legacy BUSY shape plus the canonical fields.
        ``next_snapshot`` mirrors ``restart_offset`` for the subscription
        dialect's existing consumers."""
        out = dict(self.extras)
        out["busy"] = True
        out["kind"] = self.kind
        out["state"] = self.state
        out["retry_after_ms"] = int(self.retry_after_ms)
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.restart_offset is not None:
            out["restart_offset"] = int(self.restart_offset)
            out.setdefault("next_snapshot", int(self.restart_offset))
        return out

    @classmethod
    def from_payload(cls, payload: dict, kind: str | None = None) -> "ShedInfo":
        """Parse any of the three legacy payload dialects (or a canonical
        one) back into the record. Unknown fields land in extras."""
        restart = payload.get("restart_offset")
        if restart is None:
            restart = payload.get("next_snapshot")
        return cls(
            kind=kind or payload.get("kind") or "request",
            state=payload.get("state"),
            tenant=payload.get("tenant"),
            retry_after_ms=int(payload.get("retry_after_ms") or 0),
            restart_offset=None if restart is None else int(restart),
            extras={k: v for k, v in payload.items() if k not in _CORE_KEYS},
        )


class ShedError(RuntimeError):
    """Base of every typed-shed exception: constructed from either a legacy
    payload dict or a ShedInfo, it exposes BOTH contracts — the canonical
    ``shed_info`` record and the legacy ``payload``/``retry_after_ms``
    attributes the existing clients and tests rely on."""

    default_kind = "request"

    def __init__(self, payload: "dict | ShedInfo", message: str | None = None):
        info = (
            payload
            if isinstance(payload, ShedInfo)
            # the payload's own kind wins; default_kind covers untyped
            # legacy payloads that never carried one
            else ShedInfo.from_payload(payload, kind=payload.get("kind") or self.default_kind)
        )
        self.shed_info = info
        self.payload = info.to_payload()
        self.retry_after_ms = info.retry_after_ms
        super().__init__(message or f"shed by server: {self.payload}")


class GatewayShedError(ShedError):
    """The gateway's per-tenant admission (or a downstream server whose shed
    it converted) refused this request. Carries the canonical ShedInfo; the
    legacy exception types are serializations of the same record, so a
    caller that only knows GatewayShedError still sees every shed kind."""

    default_kind = "request"
